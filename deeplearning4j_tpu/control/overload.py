"""Overload control: fair-share admission + graceful brownout.

Two admission-side mechanisms, composed into one engine hook by
:class:`OverloadGate`:

- :class:`TokenBucketAdmission` — per-tenant fair share.  Each bounded
  tenant label (the fold :class:`~..observability.fleet.TenantLabels`
  already stamped on the request) owns a token bucket refilled at
  ``rate_tokens_s``; a request charges its ``max_new_tokens`` budget.
  Over quota is a 429 (:class:`Throttled`) counted per tenant as
  ``tenant.<label>.throttled`` — one noisy tenant exhausts its OWN
  bucket, everyone else keeps their share.

- :class:`BrownoutController` — a burn-rate-driven ladder that trades
  quality for capacity BEFORE shedding load, in strict order:

  ======  ============================  ===================================
  level   action                        what a caller observes
  ======  ============================  ===================================
  0       healthy                       full quality
  1       disable speculative decoding  same tokens, lower throughput
  2       + clamp ``max_new``           shorter completions (exact prefix)
  3       + shed BACKGROUND requests    batch work 429s, interactive serves
  ======  ============================  ===================================

  Every level keeps token parity for everything that IS served: level 1
  swaps to the plain decode path (the draft never chose tokens), level 2
  serves the exact offline-sample prefix under the clamped budget, and
  level 3 rejects whole requests rather than degrading any.  Transitions
  are hysteresis-damped (enter above a threshold, exit below a lower
  one, minimum dwell between moves), logged to the flight recorder, and
  published on the ``control.brownout_level`` gauge.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from ..observability import FLIGHTREC, METRICS, TENANTS
from ..serving.batcher import ServingRejected


class Throttled(ServingRejected):
    """Admission rejected by the overload gate (fair-share quota or
    brownout shedding) — back off and retry, HTTP 429."""

    status = 429


# ------------------------------------------------------------- fair share
@dataclass(frozen=True)
class BucketConfig:
    """Per-tenant token-bucket knobs (shared by every label)."""

    rate_tokens_s: float = 200.0   # sustained per-tenant refill
    burst_tokens: float = 400.0    # bucket capacity (idle credit cap)


class TokenBucketAdmission:
    """Per-tenant token buckets over BOUNDED labels.

    Keyed by ``request.tenant`` — already folded through
    ``TenantLabels`` at submit, so the bucket map inherits the same
    cardinality bound as the per-tenant metrics (unlabelled traffic
    shares the ``""`` bucket).  ``clock`` is injectable so tests refill
    deterministically.
    """

    def __init__(self, cfg: BucketConfig = BucketConfig(),
                 clock=time.monotonic):
        self.cfg = cfg
        self._clock = clock
        self._lock = threading.Lock()
        # label -> [tokens, last_refill_t]; guarded-by: self._lock
        self._buckets: dict[str, list[float]] = {}

    def charge(self, request) -> None:
        """Debit ``request.max_new_tokens`` from its tenant's bucket or
        raise :class:`Throttled` (the bucket is left untouched on
        rejection — a throttled tenant recovers at the refill rate, not
        slower for having asked)."""
        label = getattr(request, "tenant", "") or ""
        cost = float(request.max_new_tokens)
        now = self._clock()
        with self._lock:
            bucket = self._buckets.get(label)
            if bucket is None:
                bucket = [self.cfg.burst_tokens, now]
                self._buckets[label] = bucket
            tokens, last = bucket
            tokens = min(self.cfg.burst_tokens,
                         tokens + (now - last) * self.cfg.rate_tokens_s)
            bucket[1] = now
            if cost > tokens:
                bucket[0] = tokens
                METRICS.increment("control.throttled")
                TENANTS.account("throttled", label)
                raise Throttled(
                    f"tenant over fair-share quota "
                    f"({cost:.0f} tokens asked, {tokens:.0f} available) — "
                    "retry with backoff")
            bucket[0] = tokens - cost

    def available(self, tenant_label: str = "") -> float:
        """Current token balance for a label (refilled to now)."""
        now = self._clock()
        with self._lock:
            bucket = self._buckets.get(tenant_label)
            if bucket is None:
                return self.cfg.burst_tokens
            return min(self.cfg.burst_tokens,
                       bucket[0] + (now - bucket[1]) * self.cfg.rate_tokens_s)


# -------------------------------------------------------------- brownout
@dataclass(frozen=True)
class BrownoutConfig:
    """Ladder thresholds on the SLO burn rate, with hysteresis."""

    # enter level i+1 when burn >= enter_burn[i] (monotonic ladder)
    enter_burn: tuple[float, float, float] = (1.0, 2.0, 4.0)
    exit_fraction: float = 0.5     # drop a level when burn < enter * this
    dwell_s: float = 5.0           # min residence time between transitions
    clamp_max_new: int = 16        # the level-2 max_new cap


class BrownoutController:
    """Drives the quality ladder from the burn-rate signal.

    ``engine`` is duck-typed: it needs ``set_speculative(bool)`` and
    ``set_max_new_cap(int | None)`` — the :class:`InferenceEngine`
    brownout seams.  Level 3 shedding is enforced by the
    :class:`OverloadGate` consulting :attr:`shed_background`; the
    controller itself never touches the queue.  ``clock`` is injectable
    for deterministic dwell tests.
    """

    def __init__(self, engine=None, cfg: BrownoutConfig = BrownoutConfig(),
                 clock=time.monotonic):
        self.engine = engine
        self.cfg = cfg
        self._clock = clock
        self._lock = threading.Lock()
        self._level = 0                    # guarded-by: self._lock
        self._since = clock()              # guarded-by: self._lock
        METRICS.gauge("control.brownout_level", 0.0)

    @property
    def level(self) -> int:
        with self._lock:
            return self._level

    @property
    def shed_background(self) -> bool:
        return self.level >= 3

    def _target_level(self, burn: float, current: int) -> int:
        """Hysteretic target: climb to the highest rung whose enter
        threshold ``burn`` clears; descend one rung only when burn is
        below ``exit_fraction`` of the CURRENT rung's enter threshold."""
        up = 0
        for i, thresh in enumerate(self.cfg.enter_burn):
            if burn >= thresh:
                up = i + 1
        if up > current:
            return up
        if current > 0 and \
                burn < self.cfg.enter_burn[current - 1] * self.cfg.exit_fraction:
            return current - 1   # one rung at a time — no cliff exits
        return current

    def update(self, burn: float | None) -> int:
        """Feed one burn-rate observation; returns the (possibly new)
        level.  ``None`` (no SLO data yet) holds the current level —
        absence of signal must never relax an active brownout."""
        if burn is None:
            return self.level
        with self._lock:
            current = self._level
            now = self._clock()
            if now - self._since < self.cfg.dwell_s:
                return current
            target = self._target_level(float(burn), current)
            if target == current:
                return current
            self._level = target
            self._since = now
        self._apply(current, target, float(burn))
        return target

    def _apply(self, old: int, new: int, burn: float) -> None:
        """Actuate + publish one transition (outside the level lock —
        the engine seams take their own locks)."""
        if self.engine is not None:
            self.engine.set_speculative(new < 1)
            self.engine.set_max_new_cap(
                self.cfg.clamp_max_new if new >= 2 else None)
        METRICS.increment("control.brownout_transitions")
        METRICS.gauge("control.brownout_level", float(new))
        FLIGHTREC.dump("control_brownout", extra={
            "old_level": old, "new_level": new, "burn": burn,
            "speculative": new < 1,
            "max_new_cap": self.cfg.clamp_max_new if new >= 2 else None,
            "shed_background": new >= 3})


# ------------------------------------------------------------- composition
class OverloadGate:
    """The composed admission hook: brownout shedding first (cheapest
    verdict), then fair share.  Install on an engine with
    :meth:`install` — serving stays ignorant of control (the hook seam
    points the other way)."""

    def __init__(self, bucket: TokenBucketAdmission | None = None,
                 brownout: BrownoutController | None = None):
        self.bucket = bucket
        self.brownout = brownout

    def __call__(self, request) -> None:
        if self.brownout is not None and self.brownout.shed_background \
                and getattr(request, "priority", 0) > 0:
            METRICS.increment("control.shed")
            TENANTS.account("throttled", getattr(request, "tenant", ""))
            raise Throttled(
                "background work shed under brownout — retry later")
        if self.bucket is not None:
            self.bucket.charge(request)

    def install(self, engine) -> "OverloadGate":
        engine.set_admission_hook(self)
        return self
