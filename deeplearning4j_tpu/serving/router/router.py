"""Prefix-affinity consistent-hash routing (DESIGN.md §19).

The routing key is the request's content-addressed prefix chain — the
SAME chained blake2b the :class:`~..paging.PagePool` uses
(:func:`~..paging.prefix_chain_keys`), over full pages only — truncated
to the first ``affinity_pages`` pages.  Truncation is the affinity/skew
trade: hashing the *last* chain key would scatter one tenant's requests
(every user turn extends the chain), while the first few pages are
exactly the shared system prompt whose KV pages are worth landing on.
Prompts too short for one full page fall back to a whole-prompt hash —
no cached pages exist for them anyway, so any stable spread is fine.

Dispatch walks the ring clockwise from the key, skipping quarantined
nodes (the pool's breaker), and degrades in order:

- 429 (``QueueFull`` / ``PagePoolExhausted`` / an HTTP 429 answer):
  the affinity replica is shedding — count ``router.spillover``, note it
  in the flight recorder (burst trigger), try the next node.  Spillover
  trades prefix locality for availability, which is why it is a counter
  and not silent.
- :class:`ReplicaUnavailable` / timeout / 5xx transport death: feed the
  pool's breaker (may trip quarantine) and try the next node.
- 400 / 404 / 504: the request itself is the problem — propagate, a
  different replica would answer the same.

Every attempt runs inside a ``router.route`` span nested under one
``router.request`` span, so a request that spilled twice shows three
route spans under one trace id and ``tools/trace_report.py`` renders the
router hop on the same critical path as the engine's queue/prefill/
decode/emit spans (cross-process via the ``traceparent`` header the
:class:`~..client.ServingClient` already sends).
"""

from __future__ import annotations

import hashlib
import threading
import time
from dataclasses import dataclass

from ...observability import METRICS, trace
from ...observability.flightrec import FLIGHTREC
from ...resilience.faults import FAULTS
from ..batcher import ServingRejected
from ..client import ServingError
from ..paging import prefix_chain_keys
from .replicas import (AllReplicasUnavailable, Replica, ReplicaPool,
                       ReplicaUnavailable)
from .ring import HashRing


@dataclass(frozen=True)
class RouterConfig:
    """Knobs for ring construction, affinity, spillover and the breaker."""

    page_size: int = 16          # MUST match the replicas' PagePool
    affinity_pages: int = 4      # chain prefix length the key hashes
    vnodes: int = 64             # ring points per replica
    request_timeout_s: float = 60.0
    max_spill: int | None = None  # extra nodes tried after the owner (None: all)
    probe_interval_s: float = 0.5
    probe_timeout_s: float = 2.0
    fail_threshold: int = 2      # consecutive failures -> quarantine
    recover_threshold: int = 2   # consecutive probe successes -> re-admit


class PrefixRouter:
    """Consistent-hash front tier over a :class:`ReplicaPool`."""

    def __init__(self, replicas: list[Replica],
                 cfg: RouterConfig = RouterConfig()):
        self.cfg = cfg
        self.pool = ReplicaPool(
            replicas,
            probe_interval_s=cfg.probe_interval_s,
            probe_timeout_s=cfg.probe_timeout_s,
            fail_threshold=cfg.fail_threshold,
            recover_threshold=cfg.recover_threshold)
        # the ring is immutable once published: elastic membership swaps a
        # freshly built ring ATOMICALLY (one attribute store) under
        # _ring_lock, exactly the future the HashRing docstring reserves —
        # lookups stay lockless, a reader sees the old ring or the new
        # one, never a half-mutated one
        self._ring_lock = threading.Lock()
        self.ring = HashRing(self.pool.names(), vnodes=cfg.vnodes)

    # ------------------------------------------------------------ routing
    def routing_key(self, prompt) -> str:
        """Content-addressed key for ``prompt``: the chain hash of its
        first ``affinity_pages`` FULL pages (identical to the pool's
        page addressing), else a whole-prompt fallback hash."""
        tokens = [int(t) for t in prompt]
        usable = len(tokens) - 1  # the last token is the first decode query
        keys = prefix_chain_keys(tokens, usable, self.cfg.page_size)
        if keys:
            return keys[min(len(keys), self.cfg.affinity_pages) - 1]
        return "short:" + hashlib.blake2b(
            (",".join(map(str, tokens))).encode(), digest_size=16).hexdigest()

    def route_order(self, key: str) -> list[str]:
        """Active replicas in dispatch order: the owner first, then its
        clockwise successors (the spillover / quarantine-drain order)."""
        return [n for n in self.ring.walk(key) if self.pool.is_active(n)]

    # ------------------------------------------------------------ dispatch
    def generate(self, prompt, max_new_tokens: int = 16,
                 temperature: float = 0.0, seed: int = 0,
                 eos_id: int | None = None,
                 deadline_ms: float | None = None,
                 tenant: str | None = None,
                 priority: int = 0,
                 timeout_s: float | None = None) -> dict:
        """Route one generation; returns the replica's completion dict
        plus ``replica`` (who served it) and ``spills`` (how many nodes
        were tried before it).  ``tenant`` rides the payload opaquely —
        the serving replica folds it into bounded per-tenant metrics."""
        FAULTS.maybe_fire("router.route")
        payload = {"prompt": list(prompt), "max_new_tokens": max_new_tokens,
                   "temperature": temperature, "seed": seed}
        if eos_id is not None:
            payload["eos_id"] = eos_id
        if deadline_ms is not None:
            payload["deadline_ms"] = deadline_ms
        if tenant:
            payload["tenant"] = str(tenant)
        if priority:
            payload["priority"] = int(priority)
        timeout = timeout_s if timeout_s is not None \
            else self.cfg.request_timeout_s
        key = self.routing_key(prompt)
        with trace.span("router.request", key=key[:12]):
            order = self.route_order(key)
            if not order:
                METRICS.increment("router.unroutable")
                raise AllReplicasUnavailable(
                    "no active replicas on the ring")
            if self.cfg.max_spill is not None:
                order = order[: self.cfg.max_spill + 1]
            last_rejection: ServingRejected | None = None
            for spills, name in enumerate(order):
                try:
                    rep = self.pool.replica(name)
                except KeyError:
                    continue   # removed (scale-in) after route_order ran
                self.pool.begin_request(name)
                try:
                    with trace.span("router.route", replica=name,
                                    spills=spills):
                        out = rep.generate(payload, timeout)
                except (ReplicaUnavailable, TimeoutError) as e:
                    # transport-level death: feed the breaker, drain to
                    # the next ring node
                    METRICS.increment("router.replica_errors")
                    self.pool.record_failure(name, f"dispatch: {e}")
                    last_rejection = e if isinstance(e, ServingRejected) \
                        else ReplicaUnavailable(str(e))
                    continue
                except ServingRejected as e:
                    if e.status == 429:
                        # the owner is shedding load: spill clockwise,
                        # trading prefix locality for availability
                        METRICS.increment("router.spillover")
                        FLIGHTREC.note_spillover(name)
                        last_rejection = e
                        continue
                    raise  # 504 deadline etc.: the request's problem
                except ServingError as e:
                    if e.status == 429:
                        METRICS.increment("router.spillover")
                        FLIGHTREC.note_spillover(name)
                        last_rejection = _as_rejection(e)
                        continue
                    if e.status >= 500:
                        METRICS.increment("router.replica_errors")
                        self.pool.record_failure(name, f"dispatch: {e}")
                        last_rejection = _as_rejection(e)
                        continue
                    raise  # 400/404/409: a different replica answers the same
                finally:
                    self.pool.end_request(name)
                self.pool.record_success(name)
                METRICS.increment("router.requests")
                if spills == 0:
                    # landed on the first active ring node for its key —
                    # the replica whose PagePool holds this prefix
                    METRICS.increment("router.prefix_affinity_hit")
                out["replica"] = name
                out["spills"] = spills
                return out
            raise last_rejection if last_rejection is not None else \
                AllReplicasUnavailable("all replicas failed")

    # ------------------------------------------------------ elastic scale
    def scale_up(self, replica: Replica, warm_timeout_s: float = 120.0,
                 poll_s: float = 0.05) -> None:
        """Admit a freshly built replica: wait for its engine to report
        ``warmed`` over ``/healthz``, THEN add it to the pool and publish
        a new ring.  The warm gate is the whole point — a cold replica on
        the ring inherits its keyspace segment immediately and every
        request it receives pays a compile stall (the scale-up
        TTFT-spike regression this ordering fixes).  On warm timeout the
        replica is NOT admitted (and is closed): fail safe is the old
        capacity, never a cold ring node."""
        try:
            self._await_warm(replica, warm_timeout_s, poll_s)
        except Exception:
            replica.close()
            raise
        self.pool.add_replica(replica)
        with self._ring_lock:
            self.ring = HashRing(self.pool.names(), vnodes=self.cfg.vnodes)
        METRICS.increment("router.scale_up")
        METRICS.gauge("router.pool_size", float(len(self.pool.names())))

    def scale_down(self, name: str, drain_timeout_s: float = 30.0,
                   poll_s: float = 0.02) -> Replica:
        """Drain-and-remove ``name``: quarantine-path drain first (its
        ring segment spills to the clockwise successors while in-flight
        requests finish), then detach and publish a ring without it.
        Returns the detached replica — the caller owns ``close()``.  On
        drain timeout the replica is REACTIVATED and the call raises:
        the pool can end up bigger than intended, never half-drained."""
        if len(self.pool.names()) <= 1:
            raise RuntimeError("refusing to scale down the last replica")
        self.pool.drain_replica(name)
        deadline = time.monotonic() + drain_timeout_s
        while self.pool.inflight(name) > 0:
            if time.monotonic() > deadline:
                self.pool.reactivate_replica(name)
                raise TimeoutError(
                    f"replica {name!r} did not drain within "
                    f"{drain_timeout_s}s — reactivated (fail safe)")
            time.sleep(poll_s)
        rep = self.pool.remove_replica(name)
        with self._ring_lock:
            self.ring = HashRing(self.pool.names(), vnodes=self.cfg.vnodes)
        METRICS.increment("router.scale_down")
        METRICS.gauge("router.pool_size", float(len(self.pool.names())))
        return rep

    @staticmethod
    def _await_warm(replica: Replica, timeout_s: float,
                    poll_s: float) -> None:
        """Block until the replica's health answer carries a truthy
        engine ``warmed`` flag (set at the END of ``warmup()`` — step fn
        plus the full prefill bucket ladder compiled)."""
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                health = replica.healthz(min(timeout_s, 5.0))
                if bool((health.get("engine") or {}).get("warmed")):
                    return
            except (ServingRejected, ServingError, OSError):
                pass   # still booting — keep polling until the deadline
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"replica {replica.name!r} not warmed within "
                    f"{timeout_s}s — refusing ring admission")
            time.sleep(poll_s)

    # ------------------------------------------------------------ admin
    def reload(self, step: int | None = None) -> dict[str, int]:
        """Hot-reload every ACTIVE replica (to ``step`` when given — the
        online loop's fan-out and rollback path); name -> loaded step."""
        return {name: self.pool.replica(name).reload(step)
                for name in self.pool.active_names()}

    def stats(self) -> dict:
        """Router-level view: per-replica breaker state + load."""
        out = {}
        for name in self.pool.names():
            out[name] = {"active": self.pool.is_active(name),
                         "last_probe": self.pool.last_probe(name)}
        return out

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "PrefixRouter":
        self.pool.start()
        return self

    def close(self) -> None:
        self.pool.close()

    def __enter__(self) -> "PrefixRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()


def _as_rejection(e: ServingError) -> ServingRejected:
    """Carry a downstream HTTP rejection's status through the router."""
    rej = ServingRejected(str(e))
    rej.status = e.status
    return rej
