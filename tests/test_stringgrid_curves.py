"""StringGrid/StringCluster/FingerPrintKeyer + Curves fetcher parity
(VERDICT r3 #9: the last small reference-inventory leftovers)."""

import numpy as np

from deeplearning4j_tpu.datasets import CurvesDataSetIterator
from deeplearning4j_tpu.datasets.fetchers import CurvesDataFetcher
from deeplearning4j_tpu.utils.stringgrid import (
    StringCluster, StringGrid, fingerprint, ngram_fingerprint)


def test_fingerprint_keyer():
    # the reference's doc example: these three cluster together
    assert fingerprint("Two words") == fingerprint("TWO words")
    assert fingerprint("Two words") == fingerprint("WORDS TWO")
    assert fingerprint("  Héllo,  World! ") == "hello world"
    assert ngram_fingerprint("ab ba", 2) == ngram_fingerprint("ABba", 2)


def test_string_cluster_groups_and_sorts():
    c = StringCluster(["Two words", "TWO words", "words two", "other",
                       "Other", "unique"])
    assert len(c) == 3
    clusters = c.clusters()
    # biggest cluster (3 distinct variants) first
    assert sum(clusters[0].values()) == 3 and len(clusters[0]) == 3
    assert sum(clusters[-1].values()) == 1


def test_string_grid_ops(tmp_path):
    f = tmp_path / "g.csv"
    f.write_text('a,"x,y",1\nb,z,2\nb,z,\n')
    g = StringGrid.from_file(f)
    assert g.num_columns() == 3
    assert g[0][1] == "x,y"              # quoted separator preserved
    g.remove_rows_with_empty_column(2)
    assert len(g) == 2
    assert g.get_column(0) == ["a", "b"]
    g.remove_columns(2)
    assert g.num_columns() == 2
    assert g.rows_with_column_values({"b"}, 0) == [["b", "z"]]


def test_string_grid_dedupe_by_cluster():
    g = StringGrid(",", [["ACME Inc", "1"], ["acme inc", "2"],
                         ["ACME  inc.", "3"], ["Widgets LLC", "4"]])
    g.dedupe_by_cluster(0)
    col = g.get_column(0)
    assert len(set(col[:3])) == 1          # canonicalized to one variant
    assert col[3] == "Widgets LLC"
    assert len(g.unique_rows()) == 4       # other columns still differ


def test_string_grid_word_likelihood_sort():
    g = StringGrid(",", [["rare phrase"], ["the cat"], ["the the the"]])
    g.sort_by_word_likelihood(0)
    assert g[0] == ["the the the"]          # most-typical words first


def test_curves_fetcher_shapes_and_determinism():
    it = CurvesDataSetIterator(batch=64, n_examples=128, seed=3)
    ds = it.next()
    assert ds.features.shape == (64, 784)
    assert ds.labels.shape == (64, 784)     # reconstruction corpus
    np.testing.assert_array_equal(ds.features, ds.labels)
    frac_on = (ds.features > 0).mean()
    assert 0.005 < frac_on < 0.2            # thin curves, not noise/blank
    again = CurvesDataFetcher(n_examples=128, seed=3)
    again.fetch(64)
    np.testing.assert_array_equal(again.next().features, ds.features)
    # different seed -> different curves
    other = CurvesDataFetcher(n_examples=128, seed=4)
    other.fetch(64)
    assert np.abs(other.next().features - ds.features).sum() > 0


def test_denoising_autoencoder_learns_curves():
    """The Curves corpus's actual use (deep-autoencoder pretraining,
    ``CurvesDataFetcher.java``): a denoising AE's reconstruction loss on
    curve images drops well below its starting point."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn import layers as L
    from deeplearning4j_tpu.nn.conf import LayerKind, NeuralNetConfiguration

    ds = CurvesDataSetIterator(batch=128, n_examples=128, seed=0).next()
    x = jnp.asarray(ds.features)

    conf = NeuralNetConfiguration(kind=LayerKind.AUTOENCODER, n_in=784,
                                  n_out=64, corruption_level=0.1, lr=0.5,
                                  activation="sigmoid", seed=0)
    layer = L.create_layer(conf)
    params = layer.init(jax.random.key(0))
    key = jax.random.key(1)
    loss0, _ = layer.pretrain_value_and_grad(params, x, key)

    @jax.jit
    def step(p, k):
        _, g = layer.pretrain_value_and_grad(p, x, k)
        return jax.tree_util.tree_map(lambda a, b: a - 0.5 * b, p, g)

    for i in range(80):
        key, sub = jax.random.split(key)
        params = step(params, sub)
    loss1, _ = layer.pretrain_value_and_grad(params, x, key)
    assert float(loss1) < 0.7 * float(loss0), (float(loss0), float(loss1))
