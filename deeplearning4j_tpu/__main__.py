"""Executable runner: ``python -m deeplearning4j_tpu <command> ...``.

The CLI surface the reference exposes through
``DeepLearning4jDistributedApp.main``
(``scaleout/actor/runner/DeepLearning4jDistributedApp.java:60,166`` — train
from a JSON conf, master/worker cluster roles) and the YARN ``Client``/
``Kill`` CLIs, mapped to the TPU-native runtime:

- ``train``      — build a MultiLayerNetwork from a JSON conf (``-json`` /
                   ``-jsonpath`` parity) or a zoo preset, fit on a named
                   dataset, report F1, optionally save the model.
- ``evaluate``   — load a saved model, evaluate on a named dataset.
- ``scaleout``   — run the master role of the multi-process scaleout runtime
                   (jobs from a text file, one per line), or a single worker
                   joining an existing state directory (``-t`` parity).
- ``dryrun``     — the multi-chip sharding dryrun on n virtual devices.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path


def _dataset(name: str, batch: int):
    from .datasets import (DigitsDataSetIterator, IrisDataSetIterator,
                           MnistDataSetIterator)
    name = name.lower()
    if name == "iris":
        it = IrisDataSetIterator(batch=batch)
    elif name == "digits":
        it = DigitsDataSetIterator(batch=batch)
    elif name == "mnist":
        it = MnistDataSetIterator(batch=batch)
    else:
        raise SystemExit(f"unknown dataset {name!r} (iris|digits|mnist)")
    ds = it.next()
    return ds.normalize_zero_mean_unit_variance().shuffle(seed=42)


def _cmd_train(args) -> int:
    import jax

    from .nn import MultiLayerNetwork
    from .nn.conf import MultiLayerConfiguration

    if args.json:
        conf = MultiLayerConfiguration.from_json(args.json)
    elif args.jsonpath:
        conf = MultiLayerConfiguration.from_json(Path(args.jsonpath).read_text())
    else:
        from .models import zoo
        builders = {"mlp": lambda n_in, n_out: zoo.mlp(
                        n_in, n_out, num_iterations=args.iterations),
                    "dbn": lambda n_in, n_out: zoo.dbn(
                        n_in, n_out, finetune_iterations=args.iterations)}
        if args.model not in builders:
            raise SystemExit(f"unknown --model {args.model!r} (mlp|dbn) "
                             "— or pass -json/-jsonpath")
        ds = _dataset(args.dataset, args.batch)
        net = builders[args.model](ds.features.shape[-1], ds.labels.shape[-1])
        conf = None

    if conf is not None:
        ds = _dataset(args.dataset, args.batch)
        net = MultiLayerNetwork(conf)
    net.init(jax.random.key(args.seed))
    net.fit(ds)
    ev = net.evaluate(ds)
    print(ev.stats())
    if args.out:
        net.save(args.out)
        print(f"model saved to {args.out}")
    return 0


def _cmd_evaluate(args) -> int:
    from .nn import MultiLayerNetwork
    net = MultiLayerNetwork.load(args.model_path)
    ds = _dataset(args.dataset, args.batch)
    print(net.evaluate(ds).stats())
    return 0


def _cmd_scaleout(args) -> int:
    if args.type == "kill":
        # the YARN Kill CLI analog: raise the DONE flag so the master loop
        # and every worker process wind down at their next poll
        from .parallel.procstate import FileStateTracker
        FileStateTracker(args.state_dir).finish()
        print(f"kill signalled in {args.state_dir}")
        return 0
    if args.type == "worker":
        from .parallel.procrunner import worker_loop
        worker_loop(args.state_dir, args.worker_id, args.performer)
        return 0
    from .parallel.performers import WordCountRouter
    from .parallel.procrunner import ProcessDistributedRunner
    from .parallel.scaleout import CollectionJobIterator
    if not args.jobs:
        raise SystemExit("--jobs is required for the master role")
    lines = [ln for ln in Path(args.jobs).read_text().splitlines() if ln.strip()]
    router = (WordCountRouter if args.router == "wordcount" else None)
    kw = {"router_cls": router} if router else {}
    runner = ProcessDistributedRunner(
        CollectionJobIterator(lines), args.performer,
        state_dir=args.state_dir, n_workers=args.workers, **kw)
    result = runner.run(max_wall_s=args.max_wall_s)
    print(json.dumps(result if not hasattr(result, "items")
                     else dict(result), default=str))
    return 0


def _cmd_provision(args) -> int:
    from .parallel.provision import PodSliceProvisioner, PodSliceSpec
    prov = PodSliceProvisioner(PodSliceSpec(
        name=args.name, accelerator_type=args.accelerator_type,
        zone=args.zone, spot=args.spot))
    if args.kill:
        rec = prov.teardown(dry_run=not args.apply)
        print(json.dumps(rec))
        return 0
    if not args.repo_url:
        raise SystemExit("--repo-url is required unless --kill")
    if args.apply or args.dry_run_apply:
        records = prov.apply(args.repo_url, args.train_argv,
                             dry_run=not args.apply)
        for rec in records:
            print(json.dumps(rec))
        return 0
    if args.out:
        path = prov.write_script(args.out, args.repo_url, args.train_argv)
        print(f"wrote {path}")
    else:
        print(prov.render_script(args.repo_url, args.train_argv))
    return 0


def _cmd_dryrun(args) -> int:
    from .parallel.dryrun import dryrun_multichip
    dryrun_multichip(args.devices)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m deeplearning4j_tpu")
    sub = ap.add_subparsers(dest="command", required=True)

    t = sub.add_parser("train", help="fit a network on a named dataset")
    t.add_argument("--model", default="mlp", help="zoo preset (mlp|dbn)")
    t.add_argument("-json", dest="json", help="MultiLayerConfiguration JSON")
    t.add_argument("-jsonpath", dest="jsonpath", help="path to conf JSON")
    t.add_argument("--dataset", default="iris")
    t.add_argument("--batch", type=int, default=512)
    t.add_argument("--iterations", type=int, default=150)
    t.add_argument("--seed", type=int, default=0)
    t.add_argument("--out", help="save trained model here")
    t.set_defaults(fn=_cmd_train)

    e = sub.add_parser("evaluate", help="evaluate a saved model")
    e.add_argument("model_path")
    e.add_argument("--dataset", default="iris")
    e.add_argument("--batch", type=int, default=512)
    e.set_defaults(fn=_cmd_evaluate)

    s = sub.add_parser("scaleout", help="multi-process scaleout runtime")
    s.add_argument("-t", "--type", choices=("master", "worker", "kill"),
                   default="master")
    s.add_argument("--state-dir", required=True)
    s.add_argument("--performer",
                   default="deeplearning4j_tpu.parallel.performers:WordCountPerformer")
    s.add_argument("--router", default="wordcount", choices=("wordcount", "average"))
    s.add_argument("--jobs", help="master: text file, one job per line")
    s.add_argument("--workers", type=int, default=2)
    s.add_argument("--worker-id", default="worker-0")
    s.add_argument("--max-wall-s", type=float, default=300.0)
    s.set_defaults(fn=_cmd_scaleout)

    d = sub.add_parser("dryrun", help="multi-chip sharding dryrun")
    d.add_argument("--devices", type=int, default=8)
    d.set_defaults(fn=_cmd_dryrun)

    p = sub.add_parser("provision",
                       help="render or EXECUTE a pod-slice create/bootstrap/"
                            "launch sequence (ClusterSetup parity)")
    p.add_argument("--name", default="dl4j-tpu-slice")
    p.add_argument("--accelerator-type", default="v5litepod-64")
    p.add_argument("--zone", default="us-west4-a")
    p.add_argument("--spot", action="store_true")
    p.add_argument("--repo-url", default="")
    p.add_argument("--train-argv", default="-m deeplearning4j_tpu train")
    p.add_argument("--out", help="write the script here instead of stdout")
    p.add_argument("--apply", action="store_true",
                   help="actually run gcloud (default is dry-run/render)")
    p.add_argument("--dry-run-apply", action="store_true",
                   help="print the apply command sequence without running")
    p.add_argument("--kill", action="store_true",
                   help="tear the slice down instead of bringing it up")
    p.set_defaults(fn=_cmd_provision)

    ap.add_argument("--platform", default="cpu",
                    help="jax platform (default cpu; pass 'tpu'/'' to use "
                         "the environment's accelerator)")
    args = ap.parse_args(argv)
    if args.platform:
        # Must be a config update, not just an env var: this environment's
        # boot hook registers the tunneled TPU platform at interpreter
        # start and overrides JAX_PLATFORMS (see tests/conftest.py).
        import jax
        jax.config.update("jax_platforms", args.platform)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
