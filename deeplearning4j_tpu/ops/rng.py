"""Stateless RNG utilities.

The reference threads a mutable ``RandomGenerator`` (commons-math Mersenne
twister, wrapped in ``rng/SynchronizedRandomGenerator.java`` for thread
safety) through configs (``nn/conf/NeuralNetConfiguration.java:64-68``).  On
TPU, stateful RNG does not compose with jit/vmap/scan, so the substrate is
JAX's counter-based threefry keys.  ``RngStream`` gives host-side code the
ergonomic "one generator object" feel while staying purely functional
underneath: every draw splits a fresh subkey.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class RngStream:
    """Host-side convenience wrapper over a threefry key.

    Inside jitted code always use explicit `jax.random` keys; this class is
    for eager host orchestration (weight init, data shuffles) where the
    reference used its synchronized Mersenne twister.
    """

    def __init__(self, seed_or_key):
        if isinstance(seed_or_key, int):
            self._key = jax.random.key(seed_or_key)
        else:
            self._key = seed_or_key

    def next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def split(self, n: int):
        self._key, *subs = jax.random.split(self._key, n + 1)
        return subs

    def uniform(self, shape=(), minval=0.0, maxval=1.0, dtype=jnp.float32):
        return jax.random.uniform(self.next_key(), shape, dtype, minval, maxval)

    def normal(self, shape=(), dtype=jnp.float32):
        return jax.random.normal(self.next_key(), shape, dtype)

    def permutation(self, n: int):
        return jax.random.permutation(self.next_key(), n)


def key_for(seed: int | None, default: int = 123):
    """Make a key from an optional seed (reference defaults its rng seed)."""
    return jax.random.key(default if seed is None else seed)
