"""Persistent-compilation-cache wiring: opt-in, idempotent, env-gated."""

import jax
import pytest

from deeplearning4j_tpu.parallel import compile_cache as cc


@pytest.fixture
def _restore_cache_config(monkeypatch):
    """Snapshot jax's cache config and the module's process-global state so
    these tests cannot leak a cache dir into the rest of the suite."""
    saved = {n: getattr(jax.config, n) for n in (
        "jax_enable_compilation_cache", "jax_compilation_cache_dir",
        "jax_persistent_cache_min_compile_time_secs",
        "jax_persistent_cache_min_entry_size_bytes")}
    monkeypatch.delenv(cc.ENV_DIR, raising=False)
    monkeypatch.delenv(cc.ENV_ENABLE, raising=False)
    cc._reset_for_tests()
    yield
    for n, v in saved.items():
        jax.config.update(n, v)
    cc._reset_for_tests()


def test_unset_is_noop(_restore_cache_config):
    assert cc.setup_compile_cache() is None
    assert cc.configured_dir() is None


def test_explicit_dir_configures_jax(tmp_path, _restore_cache_config):
    d = str(tmp_path / "xla")
    assert cc.setup_compile_cache(d) == d
    assert jax.config.jax_compilation_cache_dir == d
    assert jax.config.jax_enable_compilation_cache is True
    assert cc.configured_dir() == d


def test_first_dir_wins(tmp_path, _restore_cache_config):
    d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
    assert cc.setup_compile_cache(d1) == d1
    # later callers (trainer/multilayer constructors) get the configured
    # dir back — repointing a process-global cache would only split it
    assert cc.setup_compile_cache(d2) == d1
    assert jax.config.jax_compilation_cache_dir == d1


def test_env_dir_used_when_no_arg(tmp_path, monkeypatch,
                                  _restore_cache_config):
    d = str(tmp_path / "env-xla")
    monkeypatch.setenv(cc.ENV_DIR, d)
    assert cc.setup_compile_cache() == d


def test_kill_switch(tmp_path, monkeypatch, _restore_cache_config):
    monkeypatch.setenv(cc.ENV_ENABLE, "0")
    monkeypatch.setenv(cc.ENV_DIR, str(tmp_path / "xla"))
    assert cc.setup_compile_cache(str(tmp_path / "explicit")) is None
    assert cc.configured_dir() is None
