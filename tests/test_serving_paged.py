"""Paged-KV / prefix-sharing / speculative-decoding tests (DESIGN.md §17).

The contract under test is the PR-9 tentpole's acceptance bar: with
``paged=True`` (any page size, including sizes that do NOT divide
``max_len``), with ``prefix_cache=True``, with ``speculative=True``, and
with all three together, the engine's served tokens stay BITWISE
identical to ``Transformer.sample(..., kv_cache=True)`` — paging,
aliasing and draft-verify are memory/throughput techniques, never a
semantics change.  Alongside parity: page refcount hygiene (nothing
leaks, nothing aliased is ever wiped or reused), pool-exhaustion
backpressure (429, not a crash), and the chaos site for it.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models import zoo
from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   TransformerLM, decode_step,
                                                   decode_step_paged,
                                                   decode_window,
                                                   init_decode_cache,
                                                   init_paged_cache)
from deeplearning4j_tpu.observability import METRICS
from deeplearning4j_tpu.parallel.checkpoint import CheckpointManager
from deeplearning4j_tpu.resilience import FaultSpec, inject_faults
from deeplearning4j_tpu.serving import (InferenceEngine, PagePool,
                                        PagePoolExhausted, ServingConfig)


def tiny_cfg(**kw):
    kw.setdefault("vocab_size", 64)
    kw.setdefault("d_model", 32)
    kw.setdefault("n_heads", 4)
    kw.setdefault("n_layers", 2)
    kw.setdefault("d_ff", 64)
    kw.setdefault("max_len", 32)
    kw.setdefault("dtype", jnp.float32)  # exact parity comparisons
    kw.setdefault("remat", False)
    return TransformerConfig(**kw)


@pytest.fixture(scope="module")
def lm():
    cfg = tiny_cfg()
    model = TransformerLM(cfg)
    return model, model.init(jax.random.key(7))


PLANS = [([5, 1, 4], 6, 0.0, 0),
         ([2, 8, 2, 8, 2, 8, 2, 8, 2], 4, 0.8, 123),
         ([7], 5, 0.0, 3),
         ([3, 2, 1, 0, 5], 6, 1.0, 9)]


def _expected(model, params, prompt, n, temp, seed):
    return model.sample(params, prompt, n, temperature=temp,
                        key=jax.random.key(seed),
                        kv_cache=True)[len(prompt):]


def _serve_plans(model, params, scfg, plans=PLANS, **engine_kw):
    """Run ``plans`` through a fresh engine; returns the token lists.
    Starts WITHOUT warmup: the plans touch only the 8/16 buckets, so the
    cold-start ladder would compile graphs these tests never dispatch —
    admission compiles the buckets it actually needs, tokens identical."""
    engine = InferenceEngine(model, params=params, cfg=scfg, **engine_kw)
    handles = [engine.submit(p, n, temperature=t, seed=s)
               for p, n, t, s in plans]
    with engine.start(warmup=False):
        return engine, [h.result(120.0).tokens for h in handles]


# ------------------------------------------------------------------ paging
@pytest.mark.parametrize("page_size", [3, 5])
def test_paged_parity_at_odd_page_sizes(lm, page_size):
    """Bitwise token parity with page sizes that do not divide max_len —
    the partial last page and mid-page position math get no slack."""
    model, params = lm
    want = [_expected(model, params, p, n, t, s) for p, n, t, s in PLANS]
    _, got = _serve_plans(model, params,
                          ServingConfig(slots=3, resolve_every=2, paged=True,
                                        page_size=page_size))
    assert got == want


def test_paged_pool_drains_after_traffic(lm):
    """Every page acquired for a request is back on the free list after
    the request completes — the no-leak invariant PG01 lints for."""
    model, params = lm
    engine, got = _serve_plans(
        model, params,
        ServingConfig(slots=2, resolve_every=2, paged=True, page_size=4))
    want = [_expected(model, params, p, n, t, s) for p, n, t, s in PLANS]
    assert got == want
    assert engine._pool.free_count() == engine._pool.num_pages
    stats = engine.stats()
    assert stats["kv_pages_in_use"] == 0
    assert stats["kv_pages"] == engine._pool.num_pages


def test_decode_window_bitwise_vs_sequential_steps(lm):
    """The speculative verify primitive: one (B, W) window dispatch must
    leave logits AND cache bytes identical to W sequential decode_step
    calls — including at the max_len boundary, where out-of-range window
    positions must be dropped, not clamped onto the last live row."""
    model, params = lm
    cfg = model.cfg
    B, W = 2, 4
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, W)), jnp.int32)
    for start in (6, cfg.max_len - 2):        # mid-stream and boundary
        pos = jnp.full((B,), start, jnp.int32)
        cache_a = init_decode_cache(cfg, B)
        cache_b = init_decode_cache(cfg, B)
        # warm both caches identically so attention sees a real prefix
        for i in range(start):
            tok = jnp.full((B,), (i * 7) % cfg.vocab_size, jnp.int32)
            la, cache_a = decode_step(params, cache_a, tok,
                                      jnp.full((B,), i, jnp.int32), cfg)
            _, cache_b = decode_step(params, cache_b, tok,
                                     jnp.full((B,), i, jnp.int32), cfg)
        win_logits, cache_a = decode_window(params, cache_a, toks, pos, cfg)
        seq_logits = []
        for w in range(W):
            p = pos + w
            ok = p < cfg.max_len
            lw, cache_new = decode_step(
                params, cache_b, toks[:, w], jnp.minimum(p, cfg.max_len - 1),
                cfg)
            # emulate the window path's OOB-drop: rows past max_len keep
            # their cache untouched
            cache_b = jax.tree_util.tree_map(
                lambda a, b: jnp.where(
                    ok.reshape((B,) + (1,) * (a.ndim - 1)), a, b),
                cache_new, cache_b)
            seq_logits.append(lw)
        for w in range(W):
            valid = np.asarray(pos + w < cfg.max_len)
            np.testing.assert_array_equal(
                np.asarray(win_logits[:, w][valid]),
                np.asarray(seq_logits[w][valid]))
        for ca, cb in zip(cache_a, cache_b):
            np.testing.assert_array_equal(np.asarray(ca["k"]),
                                          np.asarray(cb["k"]))
            np.testing.assert_array_equal(np.asarray(ca["v"]),
                                          np.asarray(cb["v"]))


def test_decode_step_paged_matches_dense(lm):
    """Unit check under the engine: the paged single-position step is
    bitwise the dense step at an odd page size."""
    model, params = lm
    cfg = model.cfg
    B, ps = 3, 5
    n_pages = -(-cfg.max_len // ps)
    n_phys = B * n_pages + 1
    rng = np.random.default_rng(1)
    bt = jnp.asarray(rng.permutation(n_phys - 1)[:B * n_pages]
                     .reshape(B, n_pages), jnp.int32)
    dense = init_decode_cache(cfg, B)
    pages = init_paged_cache(cfg, n_phys, ps)
    for i in range(10):
        tok = jnp.asarray(rng.integers(0, cfg.vocab_size, (B,)), jnp.int32)
        pos = jnp.full((B,), i, jnp.int32)
        ld, dense = decode_step(params, dense, tok, pos, cfg)
        lp, pages = decode_step_paged(params, pages, bt, tok, pos, cfg)
        np.testing.assert_array_equal(np.asarray(ld), np.asarray(lp))


# ------------------------------------------------------------ prefix cache
@pytest.mark.lockguard
def test_prefix_refcounts_never_free_aliased_pages():
    """PagePool hygiene, run with instrumented locks: an aliased page is
    freed (and thus wipeable/reusable) only when its LAST reader lets
    go — cache eviction drops the pin, never the page."""
    pool = PagePool(num_pages=8, page_size=2)
    prompt = [1, 2, 3, 4, 5]             # 2 full pages usable (len-1 == 4)
    a = pool.alloc(3)                    # slot A's pages
    pool.insert_prefix(prompt, a, usable=4)   # pins a[0], a[1]
    assert pool.prefix_entries() == 2    # chains of length 1 and 2
    # slot B aliases the cached chain
    shared, cached = pool.lookup_prefix(prompt, usable=4)
    assert shared == a[:2] and cached == 4
    assert pool.refcount(a[0]) == 4      # A + both chain pins + B
    # slot A finishes: nothing it shares may be freed
    assert pool.decref(a) == [a[2]]      # only the unshared tail page
    grabbed = pool.alloc(6)              # exactly the free pages — no evict
    assert pool.prefix_entries() == 2
    assert not set(shared) & set(grabbed), "aliased page handed out twice"
    # allocation pressure evicts both chains (pins drop) but B's pages
    # survive the eviction, so the request STILL cannot be satisfied
    with pytest.raises(PagePoolExhausted):
        pool.alloc(1)
    assert pool.prefix_entries() == 0
    assert pool.refcount(a[0]) == 1      # B still reading, page intact
    # last reader lets go -> NOW the pages free
    assert sorted(pool.decref(shared)) == sorted(shared)
    pool.decref(grabbed)
    assert pool.free_count() == pool.num_pages


def test_prefix_exhaustion_evicts_lru_then_429s():
    pool = PagePool(num_pages=4, page_size=2)
    pages = pool.alloc(2)
    pool.insert_prefix([1, 2, 3, 4, 5], pages, usable=4)
    pool.decref(pages)                   # only the cache pins remain
    assert pool.free_count() == 2
    pool.alloc(4)                        # evicts the cache to make room
    with pytest.raises(PagePoolExhausted) as ei:
        pool.alloc(1)
    assert ei.value.status == 429


@pytest.mark.lockguard
def test_prefix_sharing_engine_parity_and_hit_rate(lm):
    """Shared system prompt across requests: bitwise parity AND a
    positive prefix hit rate, with no page leaked after the drain."""
    model, params = lm
    sys_prompt = [9, 8, 7, 6, 5, 4, 3, 2, 1, 0, 11, 12]   # 3 pages at ps=4
    plans = [(sys_prompt + [t], 5, temp, seed)
             for t, temp, seed in ((1, 0.0, 5), (2, 0.9, 17), (3, 0.0, 23),
                                   (4, 0.7, 41))]
    want = [_expected(model, params, p, n, t, s) for p, n, t, s in plans]
    engine, got = _serve_plans(
        model, params,
        ServingConfig(slots=2, resolve_every=2, paged=True, page_size=4,
                      prefix_cache=True),
        plans=plans)
    assert got == want
    stats = engine.stats()
    assert stats["prefix_hit_rate"] > 0.0
    assert stats["prefix_entries"] > 0
    # drained: every non-free page is held by a cache pin, none by slots
    pinned = engine._pool.in_use()
    assert engine._pool.free_count() == engine._pool.num_pages - pinned
    assert METRICS.snapshot()["counters"].get("serving.prefix_hits", 0) > 0


# ------------------------------------------------------------- speculative
def test_speculative_parity_good_and_bad_draft(lm):
    """Token parity must not depend on draft quality: a self-draft
    (agrees always — max accept length) and a garbage draft (random
    init — near-zero accept) serve identical tokens; only
    ``serving.spec_accept_len`` may differ."""
    model, params = lm
    want = [_expected(model, params, p, n, t, s) for p, n, t, s in PLANS]
    draft, dparams = zoo.draft_lm(model.cfg, seed=99)
    for name, dm, dp in (("self", model, params), ("garbage", draft, dparams)):
        _, got = _serve_plans(
            model, params,
            ServingConfig(slots=3, resolve_every=2, speculative=True,
                          spec_k=3),
            draft_model=dm, draft_params=dp)
        assert got == want, f"{name} draft broke parity"
        hist = METRICS.snapshot()["timers"].get("serving.spec_accept_len")
        assert hist is not None and hist["count"] > 0
        METRICS.reset()


def test_speculative_draft_divergence_chaos(lm):
    """Chaos site ``serving.draft``: garbling the draft's proposals for a
    window degrades accept length only — the served tokens still match
    the offline sampler bitwise."""
    model, params = lm
    want = [_expected(model, params, p, n, t, s) for p, n, t, s in PLANS]
    with inject_faults(FaultSpec("serving.draft", probability=1.0,
                                 max_fires=4), seed=3):
        _, got = _serve_plans(
            model, params,
            ServingConfig(slots=3, resolve_every=2, speculative=True,
                          spec_k=2),
            draft_model=model, draft_params=params)
    assert got == want
    assert METRICS.snapshot()["counters"].get("serving.draft.faults", 0) > 0


def test_combined_paged_prefix_speculative_parity(lm):
    model, params = lm
    want = [_expected(model, params, p, n, t, s) for p, n, t, s in PLANS]
    draft, dparams = zoo.draft_lm(model.cfg, seed=1)
    _, got = _serve_plans(
        model, params,
        ServingConfig(slots=3, resolve_every=2, paged=True, page_size=5,
                      prefix_cache=True, speculative=True, spec_k=2),
        draft_model=draft, draft_params=dparams)
    assert got == want


# ------------------------------------------------------------ backpressure
def test_page_pool_exhaustion_rejects_with_429_and_recovers(lm):
    """A pool too small for two concurrent sequences 429s the second
    request (admission backpressure, slot goes back, nothing leaks) and
    serves it fine once submitted after the first drains."""
    model, params = lm
    scfg = ServingConfig(slots=2, resolve_every=2, paged=True, page_size=4,
                         num_pages=9)          # warmup needs 8; 2 reqs don't fit
    prompt, n_new = [1] * 20, 8                # need 7 pages each
    want = [int(t) for t in _expected(model, params, prompt, n_new, 0.0, 7)]
    engine = InferenceEngine(model, params=params, cfg=scfg)
    h1 = engine.submit(prompt, n_new, seed=7)
    h2 = engine.submit(prompt, n_new, seed=7)
    with engine:
        ok = h1.result(120.0)
        with pytest.raises(PagePoolExhausted) as ei:
            h2.result(120.0)
        assert ei.value.status == 429
        assert ok.tokens == want
        # rejected admission leaked nothing; a later submit succeeds
        assert engine._pool.free_count() == engine._pool.num_pages
        assert engine.generate(prompt, n_new, seed=7).tokens == want
    counters = METRICS.snapshot()["counters"]
    assert counters["serving.page_pool_exhausted"] == 1
    assert counters.get("serving.engine.errors", 0) == 0


def test_page_pool_chaos_site_fixed_seed(lm):
    """Fixed-seed chaos plan for ``serving.page_pool``: the injected
    exhaustion 429s exactly one admission, leaks nothing, and later
    requests serve token-identically."""
    model, params = lm
    want = [int(t) for t in _expected(model, params, [4, 5, 6], 5, 0.0, 13)]
    engine = InferenceEngine(
        model, params=params,
        cfg=ServingConfig(slots=2, resolve_every=2, paged=True, page_size=4))
    with inject_faults(FaultSpec("serving.page_pool", probability=1.0,
                                 max_fires=1), seed=42):
        h1 = engine.submit([4, 5, 6], 5, seed=13)
        with engine:
            with pytest.raises(PagePoolExhausted):
                h1.result(120.0)
            assert engine.generate([4, 5, 6], 5, seed=13).tokens == want
            assert engine._pool.free_count() == engine._pool.num_pages
    assert METRICS.snapshot()["counters"]["serving.page_pool_exhausted"] == 1


# -------------------------------------------------------------- hot reload
def test_reload_invalidates_prefix_cache(lm, tmp_path):
    """Hot-swap must drop every cached prefix chain: the entries hold
    K/V computed under the OLD weights, and a request admitted after the
    reload that aliased them would emit tokens matching neither model.
    Post-reload shared-prefix traffic must be bitwise the NEW params'
    offline sample, and the cache re-learns under the new weights."""
    model, params_old = lm
    params_new = model.init(jax.random.key(1234))
    mgr = CheckpointManager(tmp_path / "ck", keep=3)
    mgr.save(1, params_old)
    sys_prompt = [9, 8, 7, 6, 5, 4, 3, 2, 1]           # 2 full pages at ps=4
    plans = [(sys_prompt + [t], 4, 0.0, 11 + t) for t in (1, 2)]
    engine = InferenceEngine(
        model, checkpoint=str(tmp_path / "ck"),
        cfg=ServingConfig(slots=2, resolve_every=2, paged=True, page_size=4,
                          prefix_cache=True))
    with engine:
        got = [engine.generate(p, n, temperature=t, seed=s, timeout=120.0)
               .tokens for p, n, t, s in plans]
        assert got == [_expected(model, params_old, p, n, t, s)
                       for p, n, t, s in plans]
        assert engine.stats()["prefix_entries"] > 0
        mgr.save(2, params_new)
        assert engine.reload() == 2
        assert engine.stats()["prefix_entries"] == 0   # old-weight chains gone
        got = [engine.generate(p, n, temperature=t, seed=s, timeout=120.0)
               .tokens for p, n, t, s in plans]
        assert got == [_expected(model, params_new, p, n, t, s)
                       for p, n, t, s in plans]
        assert engine.stats()["prefix_entries"] > 0    # re-learned, new weights
    # nothing leaked: every non-free page is a (new-weights) cache pin
    pinned = engine._pool.in_use()
    assert engine._pool.free_count() == engine._pool.num_pages - pinned


@pytest.mark.lockguard
def test_clear_prefix_quarantines_until_requeue():
    """Pool-level reload invalidation: clear_prefix unpins every chain.
    A page a live slot still aliases survives untouched; a page whose
    cache pin was the last reference is quarantined — NOT reallocatable
    — until the caller wipes it and hands it back with requeue."""
    pool = PagePool(num_pages=4, page_size=2)
    a = pool.alloc(2)
    pool.insert_prefix([1, 2, 3, 4, 5], a, usable=4)
    shared, cached = pool.lookup_prefix([1, 2, 3, 4, 5], usable=4)
    assert shared == a and cached == 4
    pool.decref(a)                       # original slot done; alias remains
    assert pool.clear_prefix() == [] and pool.prefix_entries() == 0
    assert pool.refcount(a[0]) == 1      # the alias keeps the page alive
    pool.decref(shared)                  # last reader: frees normally
    assert pool.free_count() == pool.num_pages
    # no alias left: the cleared pages quarantine until requeued
    b = pool.alloc(2)
    pool.insert_prefix([7, 7, 7, 7, 7], b, usable=4)
    pool.decref(b)                       # only the cache pins remain
    dead = pool.clear_prefix()
    assert sorted(dead) == sorted(b)
    assert pool.free_count() == 2        # quarantined pages NOT handed out
    with pytest.raises(PagePoolExhausted):
        pool.alloc(3)
    pool.requeue(dead)
    assert pool.free_count() == pool.num_pages


# ------------------------------------------------------------ stop/restart
def test_stop_with_inflight_then_restart_serves_clean(lm):
    """stop() with a request mid-decode must fail that caller AND fully
    reset the bookkeeping: after start() the engine has its whole slot
    range back, and the dead request's block-table row — which the
    decode step writes through whether the row is active or not — is
    parked on the trash page, never on pages reallocated to new traffic
    (served tokens stay bitwise the offline sample's)."""
    model, params = lm
    engine = InferenceEngine(
        model, params=params,
        cfg=ServingConfig(slots=2, resolve_every=2, paged=True, page_size=4))
    inflight = engine.submit([5, 1, 4], 25, seed=3)    # too long to finish
    engine._serve_once()     # admit + one 2-step segment: mid-decode
    assert engine._slots
    engine.stop()
    with pytest.raises(RuntimeError, match="request in flight"):
        inflight.result(0)
    assert engine._pool.free_count() == engine._pool.num_pages
    with engine._lock:
        assert sorted(engine._free) == [0, 1]          # full slot range back
    want = [_expected(model, params, p, n, t, s) for p, n, t, s in PLANS]
    handles = [engine.submit(p, n, temperature=t, seed=s)
               for p, n, t, s in PLANS]
    with engine:
        got = [h.result(120.0).tokens for h in handles]
    assert got == want
    assert engine._pool.free_count() == engine._pool.num_pages


# --------------------------------------------------------------- small pool
def test_warmup_and_serving_with_pool_smaller_than_max_len(lm):
    """A pool sized below pages_per_slot (legal: short-prompt traffic on
    a tight memory budget) must not wedge start(): warmup warms with the
    pages it has, short requests serve with bitwise parity, and an
    oversized request 429s at admission instead."""
    model, params = lm
    scfg = ServingConfig(slots=1, resolve_every=2, paged=True, page_size=4,
                         num_pages=3)   # 12 positions; pages_per_slot is 8
    want = [int(t) for t in _expected(model, params, [3, 1, 4], 5, 0.0, 2)]
    engine = InferenceEngine(model, params=params, cfg=scfg)
    with engine:             # start() warms up: must not exhaust the pool
        assert engine.generate([3, 1, 4], 5, seed=2,
                               timeout=120.0).tokens == want
        big = engine.submit([1] * 10, 8, seed=0)       # needs 5 pages > 3
        with pytest.raises(PagePoolExhausted) as ei:
            big.result(120.0)
        assert ei.value.status == 429
    assert engine._pool.free_count() == engine._pool.num_pages


# ---------------------------------------------------------------- kv quant
QPLANS = [([5, 1, 4], 6, 0.0, 0),            # greedy-only: the agreement
          ([7], 5, 0.0, 3),                  # floor is a top-1 statistic
          ([3, 2, 1, 0, 5], 6, 0.0, 9),
          ([3, 1, 4, 1], 8, 0.0, 1)]


def _agreement(got, want):
    hits = total = 0
    for g, w in zip(got, want):
        for a, b in zip(g, w):
            total += 1
            hits += int(a == b)
    return total, hits / max(total, 1)


def _train_decisive(cfg, period, seed=0, steps=60):
    """A briefly-trained model: decisive top-2 logit margins so the 0.999
    agreement floor measures QUANTIZATION error, not argmax coin flips on
    a random init's near-flat logits."""
    from deeplearning4j_tpu.optimize import transforms as T
    stream = np.array(period * 32, np.int32) % cfg.vocab_size
    span = cfg.max_len + 1
    n = len(stream) // span
    blocks = stream[:n * span].reshape(n, span)
    model = TransformerLM(cfg)
    tx = T.adamw(0.01)
    params = model.init(jax.random.key(seed))
    opt = model.init_opt(params, tx)
    step = model.build_train_step(tx)
    toks, tgts = jnp.asarray(blocks[:, :-1]), jnp.asarray(blocks[:, 1:])
    for _ in range(steps):
        params, opt, _ = step(params, opt, toks, tgts)
    return model, params


@pytest.fixture(scope="module")
def sharp_lm():
    return _train_decisive(tiny_cfg(vocab_size=16), [3, 1, 4, 1, 5, 9, 2, 6])


def test_kv_quant_none_stays_bitwise(lm):
    """``kv_quant=None`` is the default AND the exact path: the float
    pool serves bitwise-identical tokens — quantization is strictly
    opt-in, never a silent precision change."""
    model, params = lm
    want = [_expected(model, params, p, n, t, s) for p, n, t, s in PLANS]
    _, got = _serve_plans(
        model, params,
        ServingConfig(slots=3, resolve_every=2, paged=True, page_size=5,
                      prefix_cache=True, kv_quant=None))
    assert got == want


def test_kv_quant_requires_paged(lm):
    """Scales live beside the page pool; a dense cache has nowhere to put
    them, so the engine refuses the combination at construction."""
    model, params = lm
    with pytest.raises(ValueError, match="paged"):
        InferenceEngine(model, params=params,
                        cfg=ServingConfig(slots=2, kv_quant="int8"))
    with pytest.raises(ValueError, match="kv_quant"):
        InferenceEngine(model, params=params,
                        cfg=ServingConfig(slots=2, paged=True, page_size=4,
                                          kv_quant="int4"))


def test_int8_kv_greedy_agreement_meets_floor(sharp_lm):
    """The tentpole's serving bar: int8 KV pages keep served-token top-1
    agreement >= 0.999 against the full-precision offline sample."""
    model, params = sharp_lm
    want = [_expected(model, params, p, n, t, s) for p, n, t, s in QPLANS]
    engine, got = _serve_plans(
        model, params,
        ServingConfig(slots=3, resolve_every=2, paged=True, page_size=5,
                      prefix_cache=True, kv_quant="int8"),
        plans=QPLANS)
    total, agree = _agreement(got, want)
    assert total >= 20
    assert agree >= 0.999, f"top-1 agreement {agree} under the floor"
    assert engine.stats()["kv_quant"] == "int8"
    # the quantized pool drains like the float pool: no page leaked
    pinned = engine._pool.in_use()
    assert engine._pool.free_count() == engine._pool.num_pages - pinned


def test_int8_kv_speculative_agreement_meets_floor(sharp_lm):
    """Draft-verify windows run over the SAME quantized pool (the window
    gather dequantizes, the scatter requantizes): the combined
    paged+prefix+speculative int8 stack holds the agreement floor."""
    model, params = sharp_lm
    want = [_expected(model, params, p, n, t, s) for p, n, t, s in QPLANS]
    _, got = _serve_plans(
        model, params,
        ServingConfig(slots=3, resolve_every=2, paged=True, page_size=5,
                      prefix_cache=True, speculative=True, spec_k=2,
                      kv_quant="int8"),
        plans=QPLANS, draft_model=model, draft_params=params)
    total, agree = _agreement(got, want)
    assert total >= 20
    assert agree >= 0.999, f"top-1 agreement {agree} under the floor"


def test_int8_kv_decode_tracks_dense_within_quant_band():
    """Unit-level combo check (GQA x int8): the quantized paged step's
    logits track the dense float step within the absmax quantization
    band at every position — error stays bounded, it does not compound
    across incremental writes."""
    from deeplearning4j_tpu.models.transformer import decode_step_paged
    from deeplearning4j_tpu.ops.pallas.kv_quant import \
        init_quantized_paged_cache
    cfg = tiny_cfg(n_kv_heads=2)
    params = TransformerLM(cfg).init(jax.random.key(0))
    B, ps = 2, 5
    n_pages = -(-cfg.max_len // ps)
    n_phys = B * n_pages + 1
    rng = np.random.default_rng(4)
    bt = jnp.asarray(rng.permutation(n_phys - 1)[:B * n_pages]
                     .reshape(B, n_pages), jnp.int32)
    dense = init_decode_cache(cfg, B)
    pages = init_quantized_paged_cache(cfg, n_phys, ps, "int8")
    assert pages[0]["k"].dtype == jnp.int8
    assert pages[0]["k"].shape[2] == 2            # GQA-sized pool
    for i in range(12):
        tok = jnp.asarray(rng.integers(0, cfg.vocab_size, (B,)), jnp.int32)
        pos = jnp.full((B,), i, jnp.int32)
        ld, dense = decode_step(params, dense, tok, pos, cfg)
        lp, pages = decode_step_paged(params, pages, bt, tok, pos, cfg)
        err = float(jnp.max(jnp.abs(ld - lp)))
        assert err < 0.05, f"step {i}: logit error {err} out of quant band"


def test_reload_invalidates_quantized_prefix_pages(tmp_path):
    """Hot-swap with a quantized pool: cached prefix chains hold int8
    pages AND their scale rows computed under the OLD weights — reload
    must drop every chain (entries -> 0) and post-reload shared-prefix
    traffic must track the NEW params, re-learning the cache."""
    model, params_old = _train_decisive(tiny_cfg(vocab_size=16),
                                        [3, 1, 4, 1, 5, 9, 2, 6])
    _, params_new = _train_decisive(tiny_cfg(vocab_size=16),
                                    [2, 7, 1, 8, 2, 8, 1, 8], seed=11)
    mgr = CheckpointManager(tmp_path / "ck", keep=3)
    mgr.save(1, params_old)
    sys_prompt = [9, 8, 7, 6, 5, 4, 3, 2, 1]
    plans = [(sys_prompt + [t], 4, 0.0, 11 + t) for t in (1, 2)]
    engine = InferenceEngine(
        model, checkpoint=str(tmp_path / "ck"),
        cfg=ServingConfig(slots=2, resolve_every=2, paged=True, page_size=4,
                          prefix_cache=True, kv_quant="int8"))
    with engine:
        got = [engine.generate(p, n, temperature=t, seed=s, timeout=120.0)
               .tokens for p, n, t, s in plans]
        want = [_expected(model, params_old, p, n, t, s)
                for p, n, t, s in plans]
        assert _agreement(got, want)[1] >= 0.999
        assert engine.stats()["prefix_entries"] > 0
        mgr.save(2, params_new)
        assert engine.reload() == 2
        assert engine.stats()["prefix_entries"] == 0   # old-weight chains gone
        got = [engine.generate(p, n, temperature=t, seed=s, timeout=120.0)
               .tokens for p, n, t, s in plans]
        want = [_expected(model, params_new, p, n, t, s)
                for p, n, t, s in plans]
        assert _agreement(got, want)[1] >= 0.999, \
            "post-reload tokens do not track the NEW weights"
        assert engine.stats()["prefix_entries"] > 0    # re-learned
    pinned = engine._pool.in_use()
    assert engine._pool.free_count() == engine._pool.num_pages - pinned


def test_int8_pages_stretch_the_byte_budget(lm):
    """The capacity claim, engine-level: under a FIXED device-byte budget
    the int8 pool admits the concurrent request the float pool 429s —
    and the per-page byte accounting shows >= 1.9x pages (<= 0.53x bytes
    per slot) for the same geometry."""
    from deeplearning4j_tpu.serving.engine import kv_page_bytes
    model, params = lm
    cfg = model.cfg
    ps = 4
    float_page = kv_page_bytes(cfg, ps, None)
    int8_page = kv_page_bytes(cfg, ps, "int8")
    assert float_page / int8_page >= 1.9
    assert int8_page / float_page <= 0.53
    budget = 9 * float_page                    # 9 float pages: 2 long
    #                                            requests do NOT fit (the
    #                                            429 test above proves it)
    pages_int8 = budget // int8_page
    assert pages_int8 >= 1.9 * 9
    prompt, n_new = [1] * 20, 8                # 7 pages each
    exhausted_before = METRICS.snapshot()["counters"].get(
        "serving.page_pool_exhausted", 0)
    engine = InferenceEngine(
        model, params=params,
        cfg=ServingConfig(slots=2, resolve_every=2, paged=True, page_size=ps,
                          num_pages=int(pages_int8), kv_quant="int8"))
    h1 = engine.submit(prompt, n_new, seed=7)
    h2 = engine.submit(prompt, n_new, seed=7)
    with engine:
        r1 = h1.result(120.0)                  # neither request 429s: the
        r2 = h2.result(120.0)                  # budget now holds both
    assert len(r1.tokens) == n_new and len(r2.tokens) == n_new
    assert r1.tokens == r2.tokens              # same seed, same stream
    assert engine._pool.free_count() == engine._pool.num_pages
    assert METRICS.snapshot()["counters"].get(
        "serving.page_pool_exhausted", 0) == exhausted_before


# ------------------------------------------------------------------ wakeup
def test_cv_wakeup_beats_idle_poll(lm):
    """The batcher's condition-variable wakeup: with a pathological
    ``idle_wait_s`` the engine still admits (submit notifies) and stops
    (wake breaks the wait) in far less than the poll period."""
    model, params = lm
    engine = InferenceEngine(
        model, params=params,
        cfg=ServingConfig(slots=1, resolve_every=2, idle_wait_s=30.0))
    with engine:
        t0 = time.monotonic()
        got = engine.generate([3, 1, 4], 3, seed=2, timeout=60.0)
        admit_latency = time.monotonic() - t0
        assert got.tokens == [int(t) for t in
                              _expected(model, params, [3, 1, 4], 3, 0.0, 2)]
        assert admit_latency < 15.0      # notify hop, not the 30s poll
        t0 = time.monotonic()
    assert time.monotonic() - t0 < 15.0  # stop() woke the idle wait
