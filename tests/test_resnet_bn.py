"""Running-statistics BatchNorm (VERDICT r4 'missing' #4): train-mode
parity with the stat-less path, EMA accumulation, and batch-independent
eval-mode inference.  (The reference has no BN to cite; the ResNet north
star implies it.)"""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.models.resnet import (
    ResNet,
    ResNetConfig,
    cross_entropy,
    cross_entropy_with_stats,
    forward,
    init_batch_stats,
    init_params,
)
from deeplearning4j_tpu.optimize import transforms as T


def _cfg():
    return ResNetConfig.resnet18(num_classes=5, width=8, dtype=jnp.float32)


def _data(n=8, size=32, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, size, size, 3)).astype(np.float32)
    y = np.eye(5, dtype=np.float32)[rng.integers(0, 5, n)]
    return jnp.asarray(x), jnp.asarray(y)


def test_train_mode_with_stats_matches_stateless_path():
    """Threading the stats collection must not change the training math:
    logits and loss are identical to the r4 stat-less path."""
    cfg = _cfg()
    params = init_params(jax.random.key(0), cfg)
    stats = init_batch_stats(cfg)
    x, y = _data()
    logits0 = forward(params, x, cfg)
    logits1, new_stats = forward(params, x, cfg, stats)
    np.testing.assert_allclose(np.asarray(logits0), np.asarray(logits1),
                               atol=1e-6)
    l0 = cross_entropy(params, x, y, cfg)
    l1, _ = cross_entropy_with_stats(params, stats, x, y, cfg)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    # stats actually moved off their init toward the batch moments
    assert not np.allclose(np.asarray(new_stats["stem"]["bn"]["mean"]), 0.0)


def test_running_stats_converge_to_batch_moments():
    """Repeated train steps on one fixed batch EMA the running stats to
    that batch's moments (momentum 0.9 -> ~1 - 0.9^n of the way)."""
    cfg = _cfg()
    params = init_params(jax.random.key(0), cfg)
    stats = init_batch_stats(cfg)
    x, _ = _data()
    fwd = jax.jit(lambda p, s, xx: forward(p, xx, cfg, s))
    for _ in range(40):
        _, stats = fwd(params, stats, x)
    # recompute the stem batch moments directly
    from deeplearning4j_tpu.models.resnet import (_space_to_depth,
                                                  _stem_s2d_kernel)
    w = _stem_s2d_kernel(params["stem"]["conv"]).astype(cfg.dtype)
    h = jax.lax.conv_general_dilated(
        _space_to_depth(x).astype(cfg.dtype), w, (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC")).astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(stats["stem"]["bn"]["mean"]),
                               np.asarray(h.mean(axis=(0, 1, 2))),
                               rtol=0.05, atol=0.02)


def test_eval_mode_is_batch_independent():
    """After training, a single example's eval-mode logits are the same
    whether it is predicted alone or inside a batch of strangers — the
    r4 batch-stat eval could not do this."""
    cfg = _cfg()
    model = ResNet(cfg)
    model.init(jax.random.key(0))
    x, y = _data(n=8)
    tx = T.chain(T.momentum(0.9), T.sgd_lr(1e-2))
    step = model.train_step(tx)
    opt = (jnp.zeros((), jnp.int32), tx.init(model.params))
    params, stats = model.params, model.batch_stats
    for _ in range(5):
        params, stats, opt, loss = step(params, stats, opt, x, y)
    model.params, model.batch_stats = params, stats
    assert np.isfinite(float(loss))

    probe, _ = _data(n=4, seed=3)
    alone = model.predict_logits(probe[:1], use_running_stats=True)
    batched = model.predict_logits(probe, use_running_stats=True)[:1]
    # rtol covers f32 reduction-order noise across batch shapes; the
    # signal is the contrast with the train-mode check below
    np.testing.assert_allclose(np.asarray(alone), np.asarray(batched),
                               rtol=1e-4, atol=1e-4)
    # train-mode (batch-stat) inference does NOT have this property
    alone_t = model.predict_logits(probe[:1])
    batched_t = model.predict_logits(probe)[:1]
    assert not np.allclose(np.asarray(alone_t), np.asarray(batched_t),
                           rtol=1e-4, atol=1e-4)


def test_bn_fold_matches_unfolded():
    """bn_fold applies the identical normalization as the f32 path (folded
    per-channel affine): exact at f32 compute, close at bf16; grads flow."""
    import dataclasses

    cfg = _cfg()                                  # f32 compute dtype
    fcfg = dataclasses.replace(cfg, bn_fold=True)
    params = init_params(jax.random.key(0), cfg)
    x, y = _data()
    l0 = forward(params, x, cfg)
    l1 = forward(params, x, fcfg)
    # folding reassociates the affine ((x-m)*inv*s+b vs x*(s*inv)+(b-m*inv*s));
    # f32 rounding differences compound slightly across 18 layers
    np.testing.assert_allclose(np.asarray(l0), np.asarray(l1),
                               rtol=1e-4, atol=1e-4)

    # bf16: check at the single-BN level (end-to-end bf16-vs-bf16 diffs
    # just measure compounded rounding, not the fold's correctness)
    from deeplearning4j_tpu.models.resnet import _bn
    h = jnp.asarray(np.random.default_rng(2)
                    .standard_normal((8, 16, 16, 8)) * 3 + 1,
                    jnp.bfloat16)
    p = {"scale": jnp.asarray(np.random.default_rng(3).random(8) + 0.5,
                              jnp.float32),
         "bias": jnp.asarray(np.random.default_rng(4).random(8),
                             jnp.float32)}
    y0, _ = _bn(h, p, fold=False)
    y1, _ = _bn(h, p, fold=True)
    np.testing.assert_allclose(np.asarray(y0, np.float32),
                               np.asarray(y1, np.float32),
                               rtol=0.05, atol=0.05)

    bfcfg = dataclasses.replace(cfg, dtype=jnp.bfloat16, bn_fold=True)
    g = jax.grad(lambda pr: cross_entropy(pr, x, y, bfcfg))(params)
    assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(g))

    # running stats thread identically through the folded path
    stats = init_batch_stats(cfg)
    _, ns0 = forward(params, x, cfg, stats)
    _, ns1 = forward(params, x, fcfg, stats)
    # deeper-layer stats inherit the upstream reassociation rounding
    for a, b in zip(jax.tree.leaves(ns0), jax.tree.leaves(ns1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
