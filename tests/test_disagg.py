"""Disaggregated prefill/decode tier tests (DESIGN.md §27).

Token parity is the contract: a request prefilled on one engine,
migrated page-by-page into another engine's pool, and decoded there
must emit EXACTLY the tokens the colocated engine emits — across dense,
int8-quantized, GQA and speculative configurations.  Around that core:
the content-addressed dedup leg (a re-migrated prompt ships zero
bytes), the chaos legs (a prefill worker killed mid-request or
mid-migration only ever requeues — refcounts balance, no leaked pages),
the lockguard-checked concurrent migrate/evict interleaving, the DG01
lint seam, the prefill-role health/probe refusal, and the HTTP
``/v1/migrate`` probe + import round-trip.
"""

import textwrap
import threading
import time

import jax
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu import observability
from deeplearning4j_tpu.analysis import ACTIVE, Analyzer, active, all_rules
from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   TransformerLM)
from deeplearning4j_tpu.observability import METRICS
from deeplearning4j_tpu.resilience import FaultSpec, inject_faults
from deeplearning4j_tpu.serving import (DisaggScheduler, InferenceEngine,
                                        KVMigrator, ServingConfig,
                                        ServingClient)
from deeplearning4j_tpu.serving.disagg import export_payload
from deeplearning4j_tpu.serving.server import ModelServer


def tiny_cfg(**kw):
    kw.setdefault("vocab_size", 64)
    kw.setdefault("d_model", 32)
    kw.setdefault("n_heads", 4)
    kw.setdefault("n_layers", 2)
    kw.setdefault("d_ff", 64)
    kw.setdefault("max_len", 32)   # halves the warmup bucket ladder
    kw.setdefault("dtype", jnp.float32)   # exact parity comparisons
    kw.setdefault("remat", False)
    kw.setdefault("xent_chunk", 0)
    return TransformerConfig(**kw)


def mk_engine(model, params, role, *, draft=(None, None), **skw):
    skw.setdefault("slots", 4)
    skw.setdefault("resolve_every", 4)
    skw.setdefault("max_queue", 64)
    skw.setdefault("paged", True)
    skw.setdefault("page_size", 8)
    skw.setdefault("prefix_cache", True)
    return InferenceEngine(model, params=params, draft_model=draft[0],
                           draft_params=draft[1],
                           cfg=ServingConfig(role=role, **skw))


def ctr(name):
    return METRICS.snapshot()["counters"].get(name, 0.0)


def _expected(model, params, prompt, n, temp, seed):
    return model.sample(params, prompt, n, temperature=temp,
                        key=jax.random.key(seed),
                        kv_cache=True)[len(prompt):]


@pytest.fixture(scope="module")
def lm():
    # GQA on purpose: every fixture-driven test (dedup, concurrent
    # evict, HTTP round-trip) then exercises migrated-decode parity with
    # shared-head page layouts, which GQA attention keeps exact against
    # ``model.sample`` — so the parametrized parity test below only needs
    # the configs that CAN'T ride this fixture (int8, speculative)
    cfg = tiny_cfg(n_kv_heads=2)
    model = TransformerLM(cfg)
    return model, model.init(jax.random.key(7))


@pytest.fixture(scope="module")
def disagg(lm):
    """One prefill engine + one decode engine behind a DisaggScheduler,
    shared by the non-destructive tests in this module."""
    model, params = lm
    pf = mk_engine(model, params, "prefill")
    dec = mk_engine(model, params, "decode")
    sched = DisaggScheduler([pf], dec).start()
    yield sched, pf, dec
    sched.stop()


# page_size=8 and an 9-token prompt: usable prefix = 8 positions =
# exactly one full page, so a re-migration can claim EVERY content page
# by hash (the "fully prefix-cached prompt moves zero bytes" acceptance)
PROMPT = [1, 2, 3, 4, 5, 6, 7, 8, 9]


# ------------------------------------------------------------------ parity
@pytest.mark.parametrize("variant", ["int8kv", "speculative"])
def test_migrated_decode_token_parity(variant):
    """The contract: prefill on tier A + page migration + decode on
    tier B is token-for-token what the colocated path emits, with int8
    page layouts and speculative draft caches preserved across the
    move.  Speculative verification is exact against ``model.sample``
    (the engine's own parity suites pin that), so it compares to the
    model directly; quantized KV is NOT bitwise model.sample, so the
    int8 reference is a colocated engine with the identical config.
    (Dense-MHA parity lives in the chaos test, GQA parity in every
    fixture-driven test — see the ``lm`` fixture.)"""
    skw = {"kv_quant": "int8"} if variant == "int8kv" else {}
    model = TransformerLM(tiny_cfg())
    params = model.init(jax.random.key(7))
    draft = (None, None)
    if variant == "speculative":
        skw = {"speculative": True, "spec_k": 3}
        dm = TransformerLM(tiny_cfg(d_model=16, n_heads=2, n_layers=1,
                                    d_ff=32))
        draft = (dm, dm.init(jax.random.key(8)))
    prompt = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11]

    if variant == "int8kv":
        colo = mk_engine(model, params, "unified", draft=draft,
                         **skw).start()
        base = colo.generate(prompt, 10, temperature=0.6, seed=5,
                             timeout=120)
        colo.stop()
        want, want_reason = base.tokens, base.finish_reason
    else:
        want = _expected(model, params, prompt, 10, 0.6, 5)
        want_reason = "length"

    sched = DisaggScheduler([mk_engine(model, params, "prefill",
                                       draft=draft, **skw)],
                            mk_engine(model, params, "decode",
                                      draft=draft, **skw)).start()
    try:
        c = sched.generate(prompt, 10, temperature=0.6, seed=5, timeout=120)
    finally:
        sched.stop()
    assert c.tokens == want
    assert c.finish_reason == want_reason


# ------------------------------------------------------------------- dedup
def test_repeat_migration_is_hash_only_zero_bytes(lm, disagg):
    """Content addressing across the tier boundary: the second
    migration of an identical prompt finds every content page resident
    on the decode side and ships hash-only claims — ``pages_moved``
    stays flat while ``pages_deduped`` grows — with tokens unchanged.
    Also the per-tier queue depth gauges and the advisory plan."""
    observability.enable()
    model, params = lm
    sched, pf, dec = disagg
    want = _expected(model, params, PROMPT, 12, 0.7, 3)

    c0 = sched.generate(PROMPT, 12, temperature=0.7, seed=3, timeout=120)
    assert c0.tokens == want
    m0 = (ctr("disagg.pages_moved"), ctr("disagg.pages_deduped"))
    assert ctr("disagg.migrations") >= 1

    c1 = sched.generate(PROMPT, 12, temperature=0.7, seed=3, timeout=120)
    m1 = (ctr("disagg.pages_moved"), ctr("disagg.pages_deduped"))
    assert c1.tokens == want
    assert m1[0] - m0[0] == 0, "re-migrated prompt moved page bytes"
    assert m1[1] - m0[1] == 1, "resident content page was not claimed"

    # the advisory plan agrees with what the import just did: one
    # hash-only claim, the rest of the block-table row is bare budget
    plan = KVMigrator(dec).plan_transfer(PROMPT, 12)
    assert plan.pages_moved == 0
    assert plan.pages_deduped == 1
    assert [e.action for e in plan.entries] == ["claim", "alloc", "alloc"]

    gauges = METRICS.snapshot()["gauges"]
    assert "serving.queue.depth.prefill" in gauges
    assert "serving.queue.depth.decode" in gauges
    assert pf.stats()["role"] == "prefill"
    assert sched.stats()["role"] == "disagg"


# ------------------------------------------------------------------- chaos
def test_chaos_killed_prefill_worker_requeues_without_corruption():
    """Fixed-seed chaos plans at both disagg sites: a worker killed
    before prefill, killed after prefill (record held), and a migration
    aborted mid-transfer must each REQUEUE the request — same tokens as
    the undisturbed run — and after the dust settles both pools'
    refcounts balance to zero leaked pages.  (Own short-``max_len``
    engines: the final audit requeues without a device wipe, which is
    only legal because these pools serve no further traffic.)"""
    observability.enable()
    model = TransformerLM(tiny_cfg())
    params = model.init(jax.random.key(7))
    pf = mk_engine(model, params, "prefill")
    dec = mk_engine(model, params, "decode")
    sched = DisaggScheduler([pf], dec).start()
    try:
        prompt = [2, 3, 4, 5, 6]
        base = sched.generate(prompt, 8, temperature=0.0, seed=9,
                              timeout=120)
        # absolute dense-MHA parity for the migrated path (the variants
        # above cover int8/speculative; the fixture tests cover GQA)
        assert base.tokens == _expected(model, params, prompt, 8, 0.0, 9)
        r0 = ctr("disagg.requeues")

        # killed before the prefill ran: nothing acquired yet
        with inject_faults(FaultSpec("disagg.prefill_worker", at_step=1,
                                     max_fires=1), seed=11):
            c1 = sched.generate(prompt, 8, temperature=0.0, seed=9,
                                timeout=120)
        # killed after the prefill: the worker's record must be released
        with inject_faults(FaultSpec("disagg.prefill_worker", at_step=2,
                                     max_fires=1), seed=11):
            c2 = sched.generate(prompt, 8, temperature=0.0, seed=9,
                                timeout=120)
        # aborted mid-migration: decode-side claims already acquired
        with inject_faults(FaultSpec("disagg.migrate", at_step=2,
                                     max_fires=1), seed=12):
            c3 = sched.generate(prompt, 8, temperature=0.0, seed=9,
                                timeout=120)
        assert c1.tokens == base.tokens
        assert c2.tokens == base.tokens
        assert c3.tokens == base.tokens
        assert ctr("disagg.requeues") - r0 >= 3

        # zero-leak audit: drop the prefix-cache pins (the only
        # legitimate remaining references) and every page must come
        # back.  requeue() without a device wipe is fine here — the
        # pools serve no further traffic before teardown.
        time.sleep(0.3)
        for pool in (pf.page_pool, dec.page_pool):
            pool.requeue(pool.clear_prefix())
            assert pool.free_count() == pool.num_pages
            assert sum(pool.refcounts()) == 0
    finally:
        sched.stop()


# ------------------------------------------------------- concurrent evict
@pytest.mark.lockguard
def test_concurrent_migrate_and_evict_keep_parity(lm, disagg):
    """Migrations racing decode-side prefix eviction: clear_prefix
    between an export's probe and its import claim just downgrades
    claims to byte moves — never corrupts tokens, never deadlocks
    (lockguard watches the pool/engine lock order)."""
    model, params = lm
    sched, _pf, dec = disagg
    prompts = [[3, 1, 4, 1, 5, 9, 2, 6, 5], [2, 7, 1, 8, 2, 8],
               [1, 6, 1, 8, 0, 3, 3], [4, 4, 7, 2, 13, 5, 30]]
    want = [_expected(model, params, p, 8, 0.0, 0) for p in prompts]
    stop = threading.Event()

    def evictor():
        while not stop.is_set():
            dec.queue_wipe(dec.page_pool.clear_prefix())
            time.sleep(0.01)

    results = {}

    def worker(i):
        for _ in range(2):
            results[i] = sched.generate(prompts[i], 8, temperature=0.0,
                                        seed=0, timeout=120).tokens

    ev = threading.Thread(target=evictor)
    ev.start()
    try:
        workers = [threading.Thread(target=worker, args=(i,))
                   for i in range(len(prompts))]
        for w in workers:
            w.start()
        for w in workers:
            w.join(180.0)
    finally:
        stop.set()
        ev.join(10.0)
    assert [results[i] for i in range(len(prompts))] == want


# ---------------------------------------------------------------- DG01 lint
def lint(source, only=None, path="snippet.py"):
    rules = [all_rules()[only]] if only else None
    analyzer = Analyzer(rules=rules)
    findings = analyzer.analyze_source(textwrap.dedent(source), path)
    assert not analyzer.errors
    return findings


DG01_BAD = """
    def sneak(pool, engine, pending, pages, rows):
        claimed, n = pool.lookup_prefix([1, 2, 3], 2)
        extra = pool.alloc(4)
        engine.admit_from_pages(pending, pages=claimed + extra,
                                uploads=[])
        engine.bt = rows
"""


def test_dg01_flags_accounting_outside_the_seams():
    findings = active(lint(
        DG01_BAD, only="DG01",
        path="deeplearning4j_tpu/serving/disagg/helper.py"))
    assert len(findings) == 4            # 3 pool/engine calls + bt write
    assert all(f.rule == "DG01" for f in findings)


def test_dg01_exempts_kvmigrator_and_other_packages():
    # the same accounting inside the KVMigrator class is the seam itself
    good = """
        class KVMigrator:
            def migrate(self, pool, engine, pending, pages):
                claimed, n = pool.lookup_prefix([1, 2, 3], 2)
                engine.admit_from_pages(pending, pages=claimed, uploads=[])
    """
    assert active(lint(
        good, only="DG01",
        path="deeplearning4j_tpu/serving/disagg/migrate.py")) == []
    # and outside serving/disagg the rule does not apply at all
    assert active(lint(
        DG01_BAD, only="DG01",
        path="deeplearning4j_tpu/serving/engine.py")) == []


def test_dg01_registered_with_zero_repo_findings():
    assert "DG01" in all_rules()
    analyzer = Analyzer(rules=[all_rules()["DG01"]])
    import pathlib
    pkg = pathlib.Path(__file__).resolve().parents[1] \
        / "deeplearning4j_tpu" / "serving" / "disagg"
    findings = []
    for f in sorted(pkg.glob("*.py")):
        findings += analyzer.analyze_source(f.read_text(), str(f))
    assert [f for f in findings if f.status == ACTIVE] == []


# --------------------------------------------------------- role health/probe
def test_probe_refuses_decode_traffic_to_prefill_replicas(lm):
    """A prefill-role replica advertises its role in the health JSON and
    the pool's prober treats it as a hard failure — the breaker keeps it
    out of the decode ring instead of routing doomed requests at it."""
    from deeplearning4j_tpu.serving.router.replicas import (EngineReplica,
                                                            ReplicaPool)
    model, params = lm
    pf = mk_engine(model, params, "prefill")
    uni = mk_engine(model, params, "unified")
    assert pf.stats()["role"] == "prefill"
    assert uni.stats()["role"] == "unified"
    pool = ReplicaPool([EngineReplica("pf", pf), EngineReplica("uni", uni)],
                       fail_threshold=1)
    pool.probe_once()
    assert not pool.is_active("pf")
    assert pool.is_active("uni")


# ------------------------------------------------------------ HTTP migrate
def test_http_migrate_probe_and_import_roundtrip(lm, disagg):
    """The wire seam end to end: /healthz reports role+warmed, the
    probe answers the decode pool's resident prefix, a full export
    lands with parity, and a probe-guided re-export ships an EMPTY
    pages dict (hash-only claims over HTTP) with the same tokens.
    Rides the module engines (ModelServer serves a running engine); a
    fresh prompt keeps the first probe's ``cached_len`` at 0."""
    model, params = lm
    _sched, pf, dec = disagg
    prompt = [11, 12, 13, 14, 15, 16, 17, 18, 19]
    want = _expected(model, params, prompt, 12, 0.7, 3)
    with ModelServer(engine=dec) as server:
        client = ServingClient(port=server.port)
        health = client.healthz()
        assert health["role"] == "decode"
        assert "warmed" in health

        probe = client.migrate_probe(prompt)
        assert probe == {"cached_len": 0, "page_size": 8}

        rec = pf.prefill(prompt, 12, temperature=0.7, seed=3)
        out = client.migrate(export_payload(
            pf, rec, cached_len=probe["cached_len"]))
        assert out["tokens"] == want

        probe2 = client.migrate_probe(prompt)
        assert probe2["cached_len"] == 8   # one full page now resident
        rec2 = pf.prefill(prompt, 12, temperature=0.7, seed=3)
        payload2 = export_payload(pf, rec2,
                                  cached_len=probe2["cached_len"])
        assert payload2["pages"] == {}     # zero bytes on the wire
        out2 = client.migrate(payload2)
        assert out2["tokens"] == want
