"""Layer math unit tests (mirror of RBMTests / AutoEncoderTest / LSTMTest /
ConvolutionDownSampleLayerTest shape-and-score style)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import (
    LayerKind,
    NeuralNetConfiguration,
    RBMHiddenUnit,
    RBMVisibleUnit,
)
from deeplearning4j_tpu.nn import layers as L


def make(kind, **kw):
    return L.create_layer(NeuralNetConfiguration(kind=kind, **kw))


def test_dense_forward_shape_and_value():
    layer = make(LayerKind.DENSE, n_in=4, n_out=3, activation="sigmoid")
    params = layer.init(jax.random.key(0))
    x = jnp.ones((5, 4))
    y = layer.activate(params, x)
    assert y.shape == (5, 3)
    expected = jax.nn.sigmoid(x @ params["W"] + params["b"])
    np.testing.assert_allclose(np.asarray(y), np.asarray(expected), rtol=1e-6)


def test_param_flatten_roundtrip():
    layer = make(LayerKind.DENSE, n_in=4, n_out=3)
    params = layer.init(jax.random.key(0))
    flat = layer.flatten(params)
    assert flat.shape == (4 * 3 + 3,)
    back = layer.unflatten(flat, params)
    for k in params:
        np.testing.assert_array_equal(np.asarray(back[k]), np.asarray(params[k]))


def test_merge_params_average():
    layer = make(LayerKind.DENSE, n_in=2, n_out=2)
    p1 = layer.init(jax.random.key(0))
    p2 = layer.init(jax.random.key(1))
    avg = L.merge_params([p1, p2])
    np.testing.assert_allclose(
        np.asarray(avg["W"]), (np.asarray(p1["W"]) + np.asarray(p2["W"])) / 2, rtol=1e-6)


def test_autoencoder_pretrain_reduces_loss():
    layer = make(LayerKind.AUTOENCODER, n_in=8, n_out=4, corruption_level=0.0, lr=0.5)
    params = layer.init(jax.random.key(0))
    x = (jax.random.uniform(jax.random.key(1), (32, 8)) > 0.5).astype(jnp.float32)
    key = jax.random.key(2)
    loss0, grads = layer.pretrain_value_and_grad(params, x, key)

    @jax.jit
    def step(p):
        _, g = layer.pretrain_value_and_grad(p, x, key)
        return jax.tree_util.tree_map(lambda a, b: a - 0.5 * b, p, g)

    for _ in range(60):
        params = step(params)
    loss1, _ = layer.pretrain_value_and_grad(params, x, key)
    assert float(loss1) < float(loss0)


@pytest.mark.parametrize("visible,hidden", [
    (RBMVisibleUnit.BINARY, RBMHiddenUnit.BINARY),
    (RBMVisibleUnit.GAUSSIAN, RBMHiddenUnit.RECTIFIED),
    (RBMVisibleUnit.BINARY, RBMHiddenUnit.SOFTMAX),
    (RBMVisibleUnit.SOFTMAX, RBMHiddenUnit.BINARY),
    (RBMVisibleUnit.LINEAR, RBMHiddenUnit.GAUSSIAN),
])
def test_rbm_unit_type_combos_produce_finite_grads(visible, hidden):
    layer = make(LayerKind.RBM, n_in=6, n_out=4, visible_unit=visible,
                 hidden_unit=hidden, k=2)
    params = layer.init(jax.random.key(0))
    x = (jax.random.uniform(jax.random.key(1), (8, 6)) > 0.5).astype(jnp.float32)
    score, grads = layer.pretrain_value_and_grad(params, x, jax.random.key(2))
    assert np.isfinite(float(score))
    for g in grads.values():
        assert np.all(np.isfinite(np.asarray(g)))


def test_rbm_cd_learns_binary_data():
    """CD-1 on repetitive binary patterns should reduce reconstruction error
    (mirror of RBMTests)."""
    layer = make(LayerKind.RBM, n_in=6, n_out=4, k=1, lr=0.3)
    params = layer.init(jax.random.key(0))
    x = jnp.array([[1, 1, 1, 0, 0, 0], [1, 0, 1, 0, 0, 0], [1, 1, 1, 0, 0, 0],
                   [0, 0, 1, 1, 1, 0], [0, 0, 1, 1, 0, 0], [0, 0, 1, 1, 1, 0]],
                  dtype=jnp.float32)
    key = jax.random.key(3)
    score0, _ = layer.pretrain_value_and_grad(params, x, key)

    @jax.jit
    def step(p, k):
        _, g = layer.pretrain_value_and_grad(p, x, k)
        return jax.tree_util.tree_map(lambda a, b: a - 0.3 * b, p, g)

    for i in range(200):
        key, sub = jax.random.split(key)
        params = step(params, sub)
    score1, _ = layer.pretrain_value_and_grad(params, x, key)
    assert float(score1) < float(score0)


def test_rbm_free_energy_finite():
    layer = make(LayerKind.RBM, n_in=6, n_out=4)
    params = layer.init(jax.random.key(0))
    x = (jax.random.uniform(jax.random.key(1), (3, 6)) > 0.5).astype(jnp.float32)
    fe = layer.free_energy(params, x)
    assert fe.shape == (3,) and np.all(np.isfinite(np.asarray(fe)))


def test_lstm_forward_shapes_and_grad():
    layer = make(LayerKind.LSTM, n_in=5, n_out=5, hidden_size=8)
    params = layer.init(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (12, 5))  # (T, n_in)
    h = layer.hidden_states(params, x)
    assert h.shape == (12, 8)
    logits = layer.pre_output(params, x)
    assert logits.shape == (12, 5)
    xb = jax.random.normal(jax.random.key(2), (3, 12, 5))  # batched
    assert layer.pre_output(params, xb).shape == (3, 12, 5)
    labels = jax.nn.one_hot(jnp.arange(12) % 5, 5)
    grads = jax.grad(layer.loss)(params, x, labels)
    for g in grads.values():
        assert np.all(np.isfinite(np.asarray(g)))


def test_lstm_learns_next_token():
    """Train on a deterministic cyclic sequence; loss should drop sharply
    (autodiff replaces the reference's manual BPTT, LSTM.java:63-140)."""
    T, V = 20, 4
    seq = jnp.arange(T) % V
    x = jax.nn.one_hot(seq, V)
    y = jax.nn.one_hot((seq + 1) % V, V)
    layer = make(LayerKind.LSTM, n_in=V, n_out=V, hidden_size=16)
    params = layer.init(jax.random.key(0))
    loss0 = float(layer.loss(params, x, y))
    step = jax.jit(lambda p: jax.tree_util.tree_map(
        lambda a, g: a - 0.5 * g, p, jax.grad(layer.loss)(p, x, y)))
    for _ in range(150):
        params = step(params)
    loss1 = float(layer.loss(params, x, y))
    assert loss1 < loss0 * 0.3


def test_conv_downsample_forward_and_backward():
    layer = make(LayerKind.CONVOLUTION_DOWNSAMPLE, n_in=1, num_filters=2,
                 filter_size=(3, 3), stride=(2, 2), activation="relu")
    params = layer.init(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (4, 8, 8, 1))
    y = layer.activate(params, x)
    # conv VALID: 8-3+1=6; pool stride 2: 3
    assert y.shape == (4, 3, 3, 2)
    # backward exists (reference's is a stub returning null)
    loss = lambda p: jnp.sum(layer.activate(p, x) ** 2)
    grads = jax.grad(loss)(params)
    assert np.all(np.isfinite(np.asarray(grads["convweights"])))
    assert float(jnp.max(jnp.abs(grads["convweights"]))) > 0


def test_recursive_autoencoder():
    layer = make(LayerKind.RECURSIVE_AUTOENCODER, n_in=6, n_out=6, lr=0.1)
    params = layer.init(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (5, 6))
    loss0, grads = layer.pretrain_value_and_grad(params, x, jax.random.key(2))

    @jax.jit
    def step(p):
        _, g = layer.pretrain_value_and_grad(p, x, jax.random.key(2))
        return jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g)

    for _ in range(50):
        params = step(params)
    loss1, _ = layer.pretrain_value_and_grad(params, x, jax.random.key(2))
    assert float(loss1) < float(loss0)
    assert layer.activate(params, x).shape == (5, 6)


def test_weight_init_schemes():
    import jax as _jax
    from deeplearning4j_tpu.nn.conf import Distribution, WeightInit
    from deeplearning4j_tpu.nn.weights import init_weights
    key = _jax.random.key(0)
    w = init_weights(key, (10, 20), WeightInit.VI)
    r = np.sqrt(6) / np.sqrt(10 + 20 + 1)
    assert float(jnp.max(jnp.abs(w))) <= r + 1e-6
    assert float(jnp.max(jnp.abs(init_weights(key, (4, 4), WeightInit.ZERO)))) == 0
    wn = init_weights(key, (100, 100), WeightInit.NORMALIZED)
    assert abs(float(wn.mean())) < 1e-5
