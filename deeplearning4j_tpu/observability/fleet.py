"""Fleet observability: metric federation, tenant labels, forecasting.

Every layer below this one sees exactly one process.  This module builds
the fleet view on top of three primitives:

- :func:`parse_prometheus` / :class:`FederatedRegistry` — the inverse of
  ``MetricsRegistry.to_prometheus``: scraped ``/metrics.prom`` bodies are
  parsed back into ``{counters, gauges, histograms}`` and stored keyed
  ``(series, replica)``.  Parsing is line-tolerant — a torn scrape body
  (replica killed mid-render, truncated read) yields the lines that did
  arrive, never an exception.
- :class:`FleetScraper` — a daemon that pulls every
  :class:`~..serving.router.replicas.ReplicaPool` member's exposition
  text (quarantined replicas skipped, dead scrapes counted in
  ``fleet.scrape_errors`` and the replica marked stale) and publishes
  fleet rollups (``fleet.tokens_per_sec``, ``fleet.kv_pages_in_use``,
  ``fleet.queue_depth``, ``fleet.tokens_total``) plus per-replica
  min/median/max spreads into the *normal* registry — so
  ``TimeSeriesStore``, ``SLOEvaluator``, ``perf_gate`` and the flight
  recorder see the whole fleet without learning anything new.  The pool
  is duck-typed (``names()`` / ``is_active()`` / ``replica()``) so this
  module never imports the serving tier.  Replica clocks are never
  trusted: staleness is judged purely by the *local* receive time of the
  last good scrape, so clock skew between hosts cannot mark a live
  replica dead.  An empty scrape body means "in-process replica sharing
  the router's registry" (``EngineReplica``) — its series are already in
  the local registry, which the rollup folds in once, never per replica.
- :class:`TenantLabels` — the bounded-cardinality label contract: the
  first ``max_tenants`` distinct tenant ids are tracked exactly, every
  later id folds into ``__other__`` (``fleet.tenant_overflow`` counts
  the folds).  All per-tenant counters (``tenant.<tenant>.*``) are
  minted HERE and only here — graftlint OB03 fails any other code that
  interpolates request-derived data into a metric name, because an
  unbounded label set is a memory leak with a dashboard.
- :class:`ForecastEvaluator` — rides the ``TimeSeriesStore`` sampler
  hook like the SLO tier and extrapolates each objective's series
  against its threshold via :meth:`TimeSeriesStore.trend` (least-squares
  slope + R²), publishing ``forecast.time_to_breach.<objective>`` gauges
  and dumping a ``forecast_breach`` flight bundle when the predicted
  time-to-breach drops under the horizon — the autoscaler's leading
  indicator, firing *before* the SLO evaluator records the breach.

Disabled is free (DESIGN.md §9): every entry point returns before
allocating when ``core.enabled()`` is false.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Iterable

from . import core
from .flightrec import FLIGHTREC, FlightRecorder
from .metrics import METRICS, MetricsRegistry, _prom_name
from .slo import BUNDLE_TAIL, SLObjective
from .timeseries import TimeSeriesStore

# The fold bucket every tenant beyond the tracked top-K lands in.
OTHER_TENANT = "__other__"

# Default cap on exactly-tracked tenant labels (top-K by arrival order).
DEFAULT_MAX_TENANTS = 32

# Rollups the scraper publishes: (fleet gauge, source series in registry
# dotted form, source kind).  Counter sources keep stale replicas' last
# known value in the sum (tokens already generated stay generated);
# gauge sources drop stale replicas (a dead replica has no queue depth).
ROLLUPS: tuple[tuple[str, str, str], ...] = (
    ("fleet.tokens_per_sec", "serving.tokens_per_sec", "gauge"),
    ("fleet.kv_pages_in_use", "serving.kv_pages_in_use", "gauge"),
    ("fleet.queue_depth", "serving.queue.depth", "gauge"),
    ("fleet.tokens_total", "serving.tokens", "counter"),
)


# --------------------------------------------------------------- text format
def _parse_value(s: str) -> float:
    # to_prometheus renders NaN / +Inf / repr(float); float() reads all
    # three back (and "-Inf" for symmetry with hand-written bodies).
    return float(s)


def _strip_suffix(name: str, suffix: str) -> str:
    return name[: -len(suffix)] if name.endswith(suffix) else name


def parse_prometheus(text: str) -> dict[str, Any]:
    """Parse Prometheus text exposition (0.0.4) back into values.

    The inverse of ``MetricsRegistry.to_prometheus``: returns
    ``{"counters": {...}, "gauges": {...}, "histograms": {...}}`` keyed
    by prometheus-sanitized names with the convention suffixes stripped
    (``_total`` off counters, ``_seconds`` off histograms) so keys line
    up with ``_prom_name(dotted_name)``.  Histogram entries carry
    ``{"buckets": [(le, cumulative), ...], "sum": float, "count": float}``.

    Torn bodies are tolerated line-by-line: an unparseable line (the
    replica died mid-render, the read was truncated) is skipped and the
    lines that did arrive are returned — a scraper must degrade, never
    raise.
    """
    counters: dict[str, float] = {}
    gauges: dict[str, float] = {}
    hists: dict[str, dict[str, Any]] = {}
    types: dict[str, str] = {}

    def _hist(base: str) -> dict[str, Any]:
        key = _strip_suffix(base, "_seconds")
        return hists.setdefault(key, {"buckets": [], "sum": None,
                                      "count": None})

    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) == 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        try:
            if "{" in line:
                name, rest = line.split("{", 1)
                labels, sep, val_s = rest.partition("}")
                val_s = val_s.strip()
                if not sep or not val_s:
                    continue  # torn mid-labels or missing value
                value = _parse_value(val_s)
                if (name.endswith("_bucket") and labels.startswith('le="')
                        and labels.endswith('"')):
                    le = _parse_value(labels[4:-1])
                    _hist(name[: -len("_bucket")])["buckets"].append(
                        (le, value))
                continue  # other labeled series: nothing we render
            name, _, val_s = line.partition(" ")
            val_s = val_s.strip()
            if not name or not val_s:
                continue
            value = _parse_value(val_s)
        except ValueError:
            continue  # torn line — keep what we have
        kind = types.get(name)
        if kind == "counter":
            counters[_strip_suffix(name, "_total")] = value
        elif kind == "gauge":
            gauges[name] = value
        elif name.endswith("_sum") and types.get(name[:-4]) == "histogram":
            _hist(name[:-4])["sum"] = value
        elif name.endswith("_count") and types.get(name[:-6]) == "histogram":
            _hist(name[:-6])["count"] = value
        else:
            # TYPE header lost to the tear: a bare sample is still a
            # value — classify by convention suffix, default to gauge.
            if name.endswith("_total"):
                counters[_strip_suffix(name, "_total")] = value
            else:
                gauges[name] = value
    return {"counters": counters, "gauges": gauges, "histograms": hists}


# ---------------------------------------------------------------- federation
class FederatedRegistry:
    """Scraped metric values keyed ``(series, replica)``.

    Series names are accepted in registry dotted form or prometheus form
    (lookups normalize through ``_prom_name`` + suffix strip).  Replicas
    are marked stale when a scrape fails or the replica is quarantined;
    stale data is kept (counters remain true history) but flagged, and
    staleness is judged by *local* receive time only — replica clocks
    never enter the picture, so skew cannot fake liveness either way.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._data: dict[str, dict[str, Any]] = {}
        self._scraped_t: dict[str, float] = {}   # local receive time
        self._stale: set[str] = set()

    def update(self, replica: str, parsed: dict[str, Any],
               t: float | None = None) -> None:
        with self._lock:
            self._data[replica] = parsed
            self._scraped_t[replica] = time.time() if t is None else t
            self._stale.discard(replica)

    def mark_stale(self, replica: str) -> None:
        with self._lock:
            self._stale.add(replica)

    def forget(self, replica: str) -> None:
        with self._lock:
            self._data.pop(replica, None)
            self._scraped_t.pop(replica, None)
            self._stale.discard(replica)

    # -------------------------------------------------------------- reading
    def replicas(self) -> list[str]:
        with self._lock:
            return sorted(self._data)

    def stale(self, replica: str) -> bool:
        with self._lock:
            return replica in self._stale

    def stale_replicas(self) -> list[str]:
        with self._lock:
            return sorted(self._stale)

    def age_s(self, replica: str, now: float | None = None) -> float | None:
        """Seconds since the last good scrape (local clock)."""
        with self._lock:
            t = self._scraped_t.get(replica)
        if t is None:
            return None
        return (time.time() if now is None else now) - t

    def value(self, series: str, replica: str) -> float | None:
        """One replica's latest value for a counter or gauge series."""
        key = _strip_suffix(_strip_suffix(_prom_name(series), "_total"),
                            "_seconds")
        with self._lock:
            parsed = self._data.get(replica)
            if parsed is None:
                return None
            v = parsed["counters"].get(key)
            if v is None:
                v = parsed["gauges"].get(_prom_name(series))
            return v

    def values(self, series: str,
               include_stale: bool = True) -> dict[str, float]:
        """``{replica: value}`` for every replica carrying the series."""
        out: dict[str, float] = {}
        with self._lock:
            replicas = list(self._data)
            stale = set(self._stale)
        for r in replicas:
            if not include_stale and r in stale:
                continue
            v = self.value(series, r)
            if v is not None:
                out[r] = v
        return out

    def snapshot(self) -> dict[str, Any]:
        """Full federated view for tools: per-replica parsed data plus
        staleness and scrape-age bookkeeping."""
        with self._lock:
            return {
                "replicas": {r: {"counters": dict(p["counters"]),
                                 "gauges": dict(p["gauges"]),
                                 "stale": r in self._stale,
                                 "scraped_t": self._scraped_t.get(r)}
                             for r, p in self._data.items()},
                "stale": sorted(self._stale),
            }


# ------------------------------------------------------------------ scraping
class FleetScraper:
    """Periodically federates every pool member's ``/metrics.prom``.

    ``pool`` is duck-typed: it needs ``names()``, ``is_active(name)``
    and ``replica(name)`` where the replica answers
    ``metrics_prom(timeout_s) -> str`` — exactly the
    ``ReplicaPool``/``Replica`` surface, without importing it.  The
    scrape loop runs on its own daemon thread, never the serve thread;
    a replica that dies mid-scrape costs one bounded timeout, one
    ``fleet.scrape_errors`` increment, and a stale mark — the other
    replicas' rollups are unaffected.
    """

    def __init__(self, pool, registry: MetricsRegistry = METRICS,
                 fed: FederatedRegistry | None = None,
                 interval_s: float = 1.0, timeout_s: float = 2.0):
        self.pool = pool
        self.registry = registry
        self.fed = fed if fed is not None else FederatedRegistry()
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    # ------------------------------------------------------------ lifecycle
    def start(self) -> bool:
        if not core.enabled():
            return False
        if self._thread is not None and self._thread.is_alive():
            return False
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="dl4j-tpu-fleet-scraper", daemon=True)
        self._thread.start()
        return True

    def stop(self, timeout_s: float = 5.0) -> None:
        t = self._thread
        self._thread = None
        if t is None:
            return
        self._stop.set()
        t.join(timeout=timeout_s)

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.scrape_once()
            except Exception:
                pass  # the scraper must never take the process down

    # -------------------------------------------------------------- scraping
    def scrape_once(self) -> int:
        """One federation pass.  Returns the number of replicas whose
        exposition text was scraped and parsed (0 while disabled — and
        no work was done)."""
        if not core.enabled():
            return 0
        t0 = time.perf_counter()
        scraped = 0
        for name in self.pool.names():
            if not self.pool.is_active(name):
                self.fed.mark_stale(name)   # quarantined: skip, don't probe
                continue
            try:
                body = self.pool.replica(name).metrics_prom(self.timeout_s)
            except Exception:
                self.registry.increment("fleet.scrape_errors")
                self.fed.mark_stale(name)
                continue
            if not body:
                continue  # in-process replica: shares the local registry
            self.fed.update(name, parse_prometheus(body))
            scraped += 1
        self.registry.increment("fleet.scrapes")
        self._publish()
        self.registry.observe_time("fleet.scrape", time.perf_counter() - t0)
        return scraped

    def _publish(self) -> None:
        """Fold the federated view into the local registry as rollups."""
        snap = self.registry.snapshot()
        fed = self.fed
        for fleet_name, series, kind in ROLLUPS:
            vals = fed.values(series, include_stale=(kind == "counter"))
            local = (snap["counters"].get(series) if kind == "counter"
                     else snap["gauges"].get(series))
            if local is not None:
                vals["_local"] = float(local)
            if not vals:
                continue
            ordered = sorted(vals.values())
            self.registry.gauge(fleet_name, sum(ordered))
            self.registry.gauge(f"fleet.spread.{series}.min", ordered[0])
            self.registry.gauge(f"fleet.spread.{series}.med",
                                ordered[len(ordered) // 2])
            self.registry.gauge(f"fleet.spread.{series}.max", ordered[-1])
        stale = fed.stale_replicas()
        self.registry.gauge("fleet.replicas", len(fed.replicas()))
        self.registry.gauge("fleet.stale_replicas", len(stale))


# ------------------------------------------------------------- tenant labels
class TenantLabels:
    """Bounded-cardinality tenant labels + per-tenant accounting.

    The first ``max_tenants`` distinct tenant ids are tracked exactly;
    every later id folds into ``__other__`` and bumps
    ``fleet.tenant_overflow``.  Folding is deterministic: whether a
    tenant is exact depends only on its arrival order, never on timing.

    This class is the ONLY sanctioned path from request-derived strings
    to metric names (graftlint OB03 enforces it): call sites pass the
    raw tenant to :meth:`label` once at admission and account through
    :meth:`account` — they never build a metric name themselves.
    """

    def __init__(self, registry: MetricsRegistry = METRICS,
                 max_tenants: int = DEFAULT_MAX_TENANTS):
        self.registry = registry
        self.max_tenants = int(max_tenants)
        self._lock = threading.Lock()
        self._tracked: set[str] = set()

    def label(self, tenant: str) -> str:
        """Fold a raw tenant id to its bounded metric label ("" while
        observability is off — the no-tenant fast path stays free)."""
        if not tenant or not core.enabled():
            return ""
        if tenant == OTHER_TENANT:
            return OTHER_TENANT
        with self._lock:
            if tenant in self._tracked:
                return tenant
            if len(self._tracked) < self.max_tenants:
                self._tracked.add(tenant)
                return tenant
        self.registry.increment("fleet.tenant_overflow")
        return OTHER_TENANT

    def account(self, field: str, tenant: str, by: float = 1.0) -> None:
        """Add ``by`` to ``tenant.<label>.<field>`` (no-op for empty
        tenant or while observability is off)."""
        if not tenant or not core.enabled():
            return
        label = self.label(tenant)
        if not label:
            return
        self.registry.increment(f"tenant.{label}.{field}", by)

    def tracked(self) -> list[str]:
        with self._lock:
            return sorted(self._tracked)

    def reset(self) -> None:
        with self._lock:
            self._tracked.clear()


TENANTS = TenantLabels()


# --------------------------------------------------------------- forecasting
class ForecastEvaluator:
    """Extrapolates SLO objective series to a time-to-breach forecast.

    Rides the same ``TimeSeriesStore`` evaluator hook as
    :class:`~.slo.SLOEvaluator` and, per objective, fits a least-squares
    line (:meth:`TimeSeriesStore.trend`) over the trailing ``window_s``
    of the objective's series, then extrapolates to the threshold:

    - ``upper``: rising toward the objective → seconds until the line
      crosses it; flat, receding, or noisy (R² < ``min_r2``) → ``+inf``;
      already at/over → ``0``.
    - ``lower``: mirrored (falling toward the floor).
    - ``rate``: the published ``slo.burn_rate.<name>`` series is
      extrapolated against ``burn_threshold`` as an upper bound (the
      raw counters are cumulative and always rise; the burn rate is the
      stationary signal).

    Every pass publishes ``forecast.time_to_breach.<objective>``; a
    forecast under ``horizon_s`` dumps ONE ``forecast_breach`` flight
    bundle per cooldown — the leading indicator an autoscaler or an
    operator acts on before the SLO evaluator records the real breach.

    The model is a straight line: good for ramps (queue buildup, KV
    leak, load growth), blind to cycles and steps — which is why the
    horizon should be a few windows, not hours (DESIGN.md §24).
    """

    def __init__(self, objectives: Iterable[SLObjective],
                 store: TimeSeriesStore,
                 registry: MetricsRegistry = METRICS,
                 flightrec: FlightRecorder = FLIGHTREC,
                 horizon_s: float = 120.0, window_s: float = 60.0,
                 min_r2: float = 0.5, min_samples: int = 4,
                 breach_cooldown_s: float = 60.0, attach: bool = True):
        self.objectives = list(objectives)
        self.store = store
        self.registry = registry
        self.flightrec = flightrec
        self.horizon_s = float(horizon_s)
        self.window_s = float(window_s)
        self.min_r2 = float(min_r2)
        self.min_samples = int(min_samples)
        self.breach_cooldown_s = float(breach_cooldown_s)
        self.evaluations = 0
        self.warnings: list[str] = []          # bundle paths ("" if inhibited)
        self.last: dict[str, float] = {}
        self._last_warn_t: dict[str, float] = {}
        if attach:
            store.add_evaluator(self.evaluate)

    def _target(self, obj: SLObjective) -> tuple[str, float, str]:
        """(series, threshold, bound kind) the forecast runs against."""
        if obj.kind == "rate":
            return (f"slo.burn_rate.{obj.name}", obj.burn_threshold, "upper")
        return (obj.series, obj.objective, obj.kind)

    def time_to_breach(self, obj: SLObjective,
                       now: float | None = None) -> tuple[float, dict]:
        """(seconds until the fitted line crosses the threshold, fit
        details).  ``+inf`` when flat/receding/noisy/short-history."""
        series, threshold, kind = self._target(obj)
        detail: dict[str, Any] = {"series": series, "threshold": threshold}
        fit = self.store.trend(series, self.window_s, now=now)
        last = self.store.last(series)
        if fit is None or last is None:
            return float("inf"), detail
        slope, r2, n = fit
        detail.update(slope_per_s=slope, r2=r2, samples=n, last=last)
        if kind == "upper" and last >= threshold:
            return 0.0, detail
        if kind == "lower" and last <= threshold:
            return 0.0, detail
        if n < self.min_samples or r2 < self.min_r2:
            return float("inf"), detail
        approaching = slope > 0 if kind == "upper" else slope < 0
        if not approaching or slope == 0:
            return float("inf"), detail
        return (threshold - last) / slope, detail

    def evaluate(self, store: TimeSeriesStore | None = None,
                 now: float | None = None) -> dict[str, float]:
        """One forecast pass.  Signature matches the store's evaluator
        hook ``fn(store, t)``."""
        if not core.enabled():
            return {}
        if now is None:
            now = time.time()
        self.evaluations += 1
        out: dict[str, float] = {}
        for obj in self.objectives:
            ttb, detail = self.time_to_breach(obj, now)
            out[obj.name] = ttb
            self.registry.gauge(f"forecast.time_to_breach.{obj.name}", ttb)
            if ttb < self.horizon_s:
                self._warn(obj, ttb, detail, now)
        self.last = out
        return out

    def _warn(self, obj: SLObjective, ttb: float, detail: dict,
              now: float) -> None:
        last = self._last_warn_t.get(obj.name)
        if last is not None and now - last < self.breach_cooldown_s:
            return
        self._last_warn_t[obj.name] = now
        self.registry.increment("forecast.breach_warnings")
        tail = self.store.series(detail.get("series", obj.series))[-BUNDLE_TAIL:]
        path = self.flightrec.dump("forecast_breach", extra={
            "objective": obj.name,
            "kind": obj.kind,
            "time_to_breach_s": ttb,
            "horizon_s": self.horizon_s,
            "window_s": self.window_s,
            "fit": detail,
            "series_tail": [[t, v] for t, v in tail],
        })
        self.warnings.append(str(path) if path else "")

    def ttb_seconds(self, name: str) -> float | None:
        """Last computed time-to-breach for objective ``name``, or None
        before the first pass / for an unknown objective.  The ``+inf``
        no-breach-in-sight value passes through unchanged — callers
        (the autoscaler's ``router_signals``) compare against their own
        horizon, and ``inf`` correctly reads as healthy there."""
        return self.last.get(name)

    def status(self) -> dict[str, Any]:
        return {
            "evaluations": self.evaluations,
            "warnings": len(self.warnings),
            "horizon_s": self.horizon_s,
            "time_to_breach": dict(self.last),
        }
