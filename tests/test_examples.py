"""Smoke-run the examples/ scripts — they are user-facing documentation and
must keep working (mirror of the reference's example-shaped tests, e.g.
``MultiLayerTest`` / ``WordCountTest``)."""

import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]


def _run(script: str, timeout: float = 300.0):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, str(REPO / "examples" / script)],
        capture_output=True, text=True, timeout=timeout, env=env)
    assert proc.returncode == 0, proc.stderr[-800:]
    return proc.stdout


def test_example_iris_mlp():
    out = _run("01_iris_mlp.py")
    assert "F1 = " in out


def test_example_distributed_wordcount():
    out = _run("04_distributed_wordcount.py")
    assert "top words:" in out


def test_example_bert_finetune_sharded():
    out = _run("03_bert_finetune_sharded.py", timeout=420.0)
    assert "loss:" in out


def test_example_lstm_textgen():
    out = _run("05_lstm_textgen.py", timeout=420.0)
    assert "beam search" in out


def test_example_glove():
    out = _run("06_glove.py", timeout=420.0)
    assert "sim(apple, banana)" in out


def test_example_driver_checkpoint():
    out = _run("07_driver_checkpoint.py", timeout=420.0)
    assert "resumed" in out


def test_example_svmlight_records():
    out = _run("08_svmlight_records.py")
    assert "accuracy = " in out
    assert "(sum 400)" in out


def test_example_lm_pretrain_generate():
    out = _run("09_lm_pretrain_generate.py", timeout=420.0)
    assert "greedy: the quick" in out and "loss:" in out
    assert "kv-cached" in out
