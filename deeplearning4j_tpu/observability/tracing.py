"""Span-based structured tracing.

``with trace.span("train_step", step=i):`` opens a nestable span; nesting
propagates through a ``contextvars.ContextVar`` so spans opened on worker
threads / asyncio tasks attribute to the right parent.  Completed spans
land in a bounded in-memory buffer and (optionally) stream to a JSONL
event log.  The buffer exports as Chrome trace-event JSON — complete
("ph":"X") events with microsecond ``ts``/``dur``, ``pid`` = JAX process
index (host index on a pod slice), ``tid`` = OS thread id — loadable in
Perfetto / chrome://tracing.

Zero-overhead contract: when observability is disabled, ``span()`` returns
the shared no-op context manager (no allocation); see ``core``.
"""

from __future__ import annotations

import contextvars
import json
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any

from . import core

_EPOCH = time.perf_counter()
_MAX_EVENTS = 65536

# Innermost-open-span chain, per context (thread / task).
_current: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "dl4j_tpu_current_span", default=None)

_process_index: int | None = None


def _pid() -> int:
    """JAX process index (host index), lazily resolved; 0 without jax."""
    global _process_index
    if _process_index is None:
        try:
            import jax
            _process_index = int(jax.process_index())
        except Exception:
            _process_index = 0
    return _process_index


class Span:
    """One nestable timed region.  Use via ``tracer.span(...)``."""

    __slots__ = ("tracer", "name", "attrs", "parent", "depth",
                 "t0_us", "tid", "_token")

    def __init__(self, tracer: "Tracer", name: str, attrs: dict[str, Any]):
        self.tracer = tracer
        self.name = name
        self.attrs = attrs
        self.parent: Span | None = None
        self.depth = 0

    def set(self, **attrs) -> None:
        """Attach/override attributes while the span is open."""
        self.attrs.update(attrs)

    def __enter__(self) -> "Span":
        self.parent = _current.get()
        self.depth = self.parent.depth + 1 if self.parent is not None else 0
        self._token = _current.set(self)
        self.tid = threading.get_ident()
        self.t0_us = (time.perf_counter() - _EPOCH) * 1e6
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        dur_us = (time.perf_counter() - _EPOCH) * 1e6 - self.t0_us
        _current.reset(self._token)
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        self.tracer._record(self, dur_us)
        return False


class Tracer:
    """Collects completed spans; exports Chrome trace JSON and JSONL."""

    def __init__(self, max_events: int = _MAX_EVENTS):
        self._lock = threading.Lock()
        self.events: deque[dict[str, Any]] = deque(maxlen=max_events)
        self._jsonl: Any = None  # open file handle when streaming

    # ------------------------------------------------------------- record
    def span(self, name: str, **attrs):
        """Open a span context manager (no-op singleton when disabled)."""
        if not core.enabled():
            return core.NOOP_SPAN
        return Span(self, name, attrs)

    def _record(self, span: Span, dur_us: float) -> None:
        ev = {
            "name": span.name,
            "ph": "X",
            "ts": span.t0_us,
            "dur": dur_us,
            "pid": _pid(),
            "tid": span.tid,
            "args": dict(span.attrs,
                         parent=span.parent.name if span.parent else None,
                         depth=span.depth),
        }
        with self._lock:
            self.events.append(ev)
            if self._jsonl is not None:
                self._jsonl.write(json.dumps(ev) + "\n")
                self._jsonl.flush()

    # ------------------------------------------------------------- export
    def to_chrome_trace(self) -> dict[str, Any]:
        """Perfetto/chrome://tracing-loadable trace object."""
        with self._lock:
            return {"traceEvents": list(self.events), "displayTimeUnit": "ms"}

    def save_chrome_trace(self, path: str | Path) -> Path:
        path = Path(path)
        path.write_text(json.dumps(self.to_chrome_trace()))
        return path

    def export_jsonl(self, path: str | Path) -> Path:
        """Dump the buffered events as one JSON object per line."""
        path = Path(path)
        with self._lock:
            with open(path, "w") as f:
                for ev in self.events:
                    f.write(json.dumps(ev) + "\n")
        return path

    def stream_jsonl(self, path: str | Path) -> None:
        """Append each completed span to ``path`` as it closes (crash-safe
        event log; survives a process that never reaches export)."""
        with self._lock:
            if self._jsonl is not None:
                self._jsonl.close()
            self._jsonl = open(path, "a")

    def stop_stream(self) -> None:
        with self._lock:
            if self._jsonl is not None:
                self._jsonl.close()
                self._jsonl = None

    def clear(self) -> None:
        with self._lock:
            self.events.clear()


TRACER = Tracer()


def span(name: str, **attrs):
    """Module-level convenience: ``with trace.span("fit", epochs=2):``."""
    return TRACER.span(name, **attrs)


def profiler_trace(log_dir: str):
    """Context manager: JAX profiler trace (XPlane) to ``log_dir`` — the
    XLA-level companion to the host-side spans above."""
    import jax

    class _Trace:
        def __enter__(self):
            jax.profiler.start_trace(log_dir)
            return self

        def __exit__(self, *exc):
            jax.profiler.stop_trace()

    return _Trace()
