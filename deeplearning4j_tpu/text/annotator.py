"""Text annotators: POS tagging, stemming, sentence annotation.

Capability parity with the reference's UIMA annotator pipeline
(``/root/reference/deeplearning4j-scaleout/deeplearning4j-nlp/src/main/java/
org/deeplearning4j/text/annotator/PoStagger.java``, ``StemmerAnnotator.java``,
``SentenceAnnotator.java``, ``TokenizerAnnotator.java``) — there those are
thin UIMA/CAS adapters over external OpenNLP models (a maxent POS model, a
Snowball stemmer, a sentence detector).  Here the annotators are
self-contained:

- :class:`AveragedPerceptronTagger` — trainable averaged-perceptron POS
  tagger (Collins 2002), greedy decode plus per-token score emission for
  Viterbi smoothing (``utils/viterbi.py``).  A vendored tagged sample
  (``data/pos_sample.txt``) trains a usable default offline (zero egress).
- :class:`PorterStemmer` / :class:`StemmerPreProcess` — the classic Porter
  (1980) algorithm as a ``TokenPreProcess``, pluggable anywhere the
  tokenization SPI accepts a preprocessor (≡ ``StemmerAnnotator``).
- :class:`SentenceAnnotator` — abbreviation-aware rule splitter
  (≡ ``SentenceAnnotator.java`` / OpenNLP sentence detector role).
- :class:`TokenizerAnnotator` — adapter from the tokenizer factory SPI to
  the annotator interface (≡ ``TokenizerAnnotator.java``).

Feeds ``text/windows.py`` (labeled context windows) and
``utils/viterbi.py`` (sequence smoothing), which previously had no
upstream tagger.
"""

from __future__ import annotations

import random
import re
from collections import defaultdict
from pathlib import Path

import numpy as np

_DATA = Path(__file__).parent / "data"


# --------------------------------------------------------------------------- stemmer

class PorterStemmer:
    """Porter (1980) suffix-stripping stemmer, implemented from the
    published algorithm description."""

    _VOWELS = set("aeiou")

    def _cons(self, w, i):
        c = w[i]
        if c in self._VOWELS:
            return False
        if c == "y":
            return i == 0 or not self._cons(w, i - 1)
        return True

    def _measure(self, stem):
        """m = number of VC sequences in [C](VC)^m[V]."""
        forms = "".join("c" if self._cons(stem, i) else "v"
                        for i in range(len(stem)))
        return len(re.findall("vc", forms))

    def _has_vowel(self, stem):
        return any(not self._cons(stem, i) for i in range(len(stem)))

    def _double_cons(self, w):
        return (len(w) >= 2 and w[-1] == w[-2] and self._cons(w, len(w) - 1))

    def _cvc(self, w):
        return (len(w) >= 3 and self._cons(w, len(w) - 3)
                and not self._cons(w, len(w) - 2)
                and self._cons(w, len(w) - 1) and w[-1] not in "wxy")

    def _replace(self, w, suf, rep, m_min=0):
        if w.endswith(suf):
            stem = w[: len(w) - len(suf)]
            if self._measure(stem) > m_min:
                return stem + rep, True
            return w, True        # matched but condition failed: stop here
        return w, False

    def stem(self, word: str) -> str:
        w = word.lower()
        if len(w) <= 2:
            return w
        # step 1a
        for suf, rep in (("sses", "ss"), ("ies", "i"), ("ss", "ss"), ("s", "")):
            if w.endswith(suf):
                w = w[: len(w) - len(suf)] + rep
                break
        # step 1b
        if w.endswith("eed"):
            if self._measure(w[:-3]) > 0:
                w = w[:-1]
        else:
            flag = False
            for suf in ("ed", "ing"):
                if w.endswith(suf) and self._has_vowel(w[: len(w) - len(suf)]):
                    w = w[: len(w) - len(suf)]
                    flag = True
                    break
            if flag:
                if w.endswith(("at", "bl", "iz")):
                    w += "e"
                elif self._double_cons(w) and w[-1] not in "lsz":
                    w = w[:-1]
                elif self._measure(w) == 1 and self._cvc(w):
                    w += "e"
        # step 1c
        if w.endswith("y") and self._has_vowel(w[:-1]):
            w = w[:-1] + "i"
        # step 2
        for suf, rep in (("ational", "ate"), ("tional", "tion"),
                         ("enci", "ence"), ("anci", "ance"), ("izer", "ize"),
                         ("abli", "able"), ("alli", "al"), ("entli", "ent"),
                         ("eli", "e"), ("ousli", "ous"), ("ization", "ize"),
                         ("ation", "ate"), ("ator", "ate"), ("alism", "al"),
                         ("iveness", "ive"), ("fulness", "ful"),
                         ("ousness", "ous"), ("aliti", "al"),
                         ("iviti", "ive"), ("biliti", "ble")):
            nw, matched = self._replace(w, suf, rep)
            if matched:
                w = nw
                break
        # step 3
        for suf, rep in (("icate", "ic"), ("ative", ""), ("alize", "al"),
                         ("iciti", "ic"), ("ical", "ic"), ("ful", ""),
                         ("ness", "")):
            nw, matched = self._replace(w, suf, rep)
            if matched:
                w = nw
                break
        # step 4
        for suf in ("al", "ance", "ence", "er", "ic", "able", "ible", "ant",
                    "ement", "ment", "ent", "ou", "ism", "ate", "iti",
                    "ous", "ive", "ize"):
            if w.endswith(suf):
                if self._measure(w[: len(w) - len(suf)]) > 1:
                    w = w[: len(w) - len(suf)]
                break
        else:
            if w.endswith("ion") and len(w) > 3 and w[-4] in "st" \
                    and self._measure(w[:-3]) > 1:
                w = w[:-3]
        # step 5a
        if w.endswith("e"):
            stem = w[:-1]
            m = self._measure(stem)
            if m > 1 or (m == 1 and not self._cvc(stem)):
                w = stem
        # step 5b
        if self._double_cons(w) and w.endswith("l") and self._measure(w) > 1:
            w = w[:-1]
        return w


class StemmerPreProcess:
    """``TokenPreProcess`` that stems (≡ ``StemmerAnnotator.java`` wrapping
    the Snowball stemmer as a CAS annotator) — drop into any tokenizer
    factory: ``DefaultTokenizerFactory(pre=StemmerPreProcess())``."""

    def __init__(self, stemmer: PorterStemmer | None = None, lower=True):
        self.stemmer = stemmer or PorterStemmer()
        self.lower = lower

    def __call__(self, token: str) -> str:
        return self.stemmer.stem(token.lower() if self.lower else token)


# --------------------------------------------------------------------------- sentences

class SentenceAnnotator:
    """Abbreviation-aware sentence boundary splitter (the reference's
    ``SentenceAnnotator.java`` fills this role via OpenNLP's detector)."""

    # titles precede a (capitalized) name and never end a sentence; other
    # abbreviations CAN end one — for those, split iff the next word is
    # capitalized (the standard detector heuristic)
    _TITLES = {"mr", "mrs", "ms", "dr", "prof", "sr", "jr", "st"}
    _ABBREV = {"vs", "etc", "inc", "ltd", "co", "e.g", "i.e", "u.s",
               "a.m", "p.m"}
    _BOUNDARY = re.compile(r"([.!?]+)(\s+|$)")

    def annotate(self, text: str) -> list[str]:
        sentences, start = [], 0
        for m in self._BOUNDARY.finditer(text):
            prev = text[start:m.end(1)]
            last_word = prev.rstrip(".!?").rsplit(None, 1)
            token = last_word[-1].lower().rstrip(".") if last_word else ""
            nxt = text[m.end():m.end() + 1]
            if token in self._TITLES:
                continue                     # never a boundary
            if token in self._ABBREV and not nxt.isupper():
                continue                     # mid-sentence abbreviation
            s = text[start:m.end(1)].strip()
            if s:
                sentences.append(s)
            start = m.end()
        tail = text[start:].strip()
        if tail:
            sentences.append(tail)
        return sentences

    __call__ = annotate


class TokenizerAnnotator:
    """Adapter: tokenizer-factory SPI -> annotator interface
    (≡ ``TokenizerAnnotator.java``)."""

    def __init__(self, factory=None):
        if factory is None:
            from .tokenization import DefaultTokenizerFactory
            factory = DefaultTokenizerFactory()
        self.factory = factory

    def annotate(self, text: str) -> list[str]:
        return self.factory.create(text).get_tokens()

    __call__ = annotate


# --------------------------------------------------------------------------- POS tagger

def _normalize(word: str) -> str:
    if any(ch.isdigit() for ch in word):
        return "!DIGIT" if word.isdigit() else "!MIXEDDIGIT"
    return word.lower()


class AveragedPerceptronTagger:
    """Averaged-perceptron POS tagger (Collins 2002; the standard
    lightweight trainable tagger).  Plays the reference ``PoStagger.java``
    role without the external OpenNLP maxent model: train on any
    word/TAG-formatted corpus, or call :meth:`default` for one trained on
    the vendored sample."""

    START = ("-START-", "-START2-")

    def __init__(self):
        self.weights: dict[str, dict[str, float]] = {}
        self.classes: list[str] = []
        self.tagdict: dict[str, str] = {}     # unambiguous-word shortcut

    # -- features -------------------------------------------------------
    def _features(self, i, word, context, prev, prev2):
        w = context[i]
        feats = {
            "bias": 1.0,
            f"word={w}": 1.0,
            f"suf3={w[-3:]}": 1.0,
            f"suf2={w[-2:]}": 1.0,
            f"pre1={w[:1]}": 1.0,
            f"prevtag={prev}": 1.0,
            f"prev2tags={prev2}|{prev}": 1.0,
            f"prevtag+word={prev}|{w}": 1.0,
            f"prevword={context[i - 1]}": 1.0,
            f"prevsuf3={context[i - 1][-3:]}": 1.0,
            f"nextword={context[i + 1]}": 1.0,
            f"nextsuf3={context[i + 1][-3:]}": 1.0,
        }
        if word and word[0].isupper():
            feats["shape=cap"] = 1.0
        return feats

    def _score(self, feats):
        scores = defaultdict(float)
        for f, v in feats.items():
            if f in self.weights:
                for tag, w in self.weights[f].items():
                    scores[tag] += w * v
        return scores

    # -- inference ------------------------------------------------------
    def tag(self, tokens: list[str]) -> list[tuple[str, str]]:
        """Greedy left-to-right decode (the tagdict shortcuts unambiguous
        words exactly like the textbook implementation)."""
        prev, prev2 = self.START
        context = ([self.START[0], self.START[1]]
                   + [_normalize(t) for t in tokens] + ["-END-", "-END2-"])
        out = []
        for i, tok in enumerate(tokens):
            tag = self.tagdict.get(_normalize(tok))
            if tag is None:
                feats = self._features(i + 2, tok, context, prev, prev2)
                scores = self._score(feats)
                tag = max(self.classes,
                          key=lambda t: (scores.get(t, 0.0), t))
            out.append((tok, tag))
            prev2, prev = prev, tag
        return out

    def emissions(self, tokens: list[str]) -> np.ndarray:
        """(T, n_classes) softmax-normalized scores for Viterbi smoothing
        (``utils/viterbi.py``) — the greedy path's scores, exposed."""
        prev, prev2 = self.START
        context = ([self.START[0], self.START[1]]
                   + [_normalize(t) for t in tokens] + ["-END-", "-END2-"])
        probs = np.zeros((len(tokens), len(self.classes)))
        for i, tok in enumerate(tokens):
            fixed = self.tagdict.get(_normalize(tok))
            if fixed is not None:
                # tagdict words are never perceptron-trained (the trainer
                # shortcuts them exactly like tag() does): peak the
                # distribution on the dictionary tag instead of exposing
                # untrained scores
                j = self.classes.index(fixed)
                probs[i] = (1.0 - 0.95) / max(1, len(self.classes) - 1)
                probs[i, j] = 0.95
                probs[i] /= probs[i].sum()   # exact with 1 class, no-op else
            else:
                feats = self._features(i + 2, tok, context, prev, prev2)
                scores = self._score(feats)
                row = np.array([scores.get(t, 0.0) for t in self.classes])
                row = np.exp(row - row.max())
                probs[i] = row / row.sum()
            tag = self.classes[int(np.argmax(probs[i]))]
            prev2, prev = prev, tag
        return probs

    def annotate(self, text: str) -> list[tuple[str, str]]:
        from .tokenization import DefaultTokenizer
        return self.tag(DefaultTokenizer(text).get_tokens())

    # -- training -------------------------------------------------------
    def train(self, sentences: list[list[tuple[str, str]]],
              n_iter: int = 8, seed: int = 0) -> None:
        """Averaged-perceptron training on (word, tag) sentences."""
        self.classes = sorted({t for s in sentences for _, t in s})
        self._make_tagdict(sentences)
        totals: dict[tuple[str, str], float] = defaultdict(float)
        tstamps: dict[tuple[str, str], int] = defaultdict(int)
        instances = 0
        rng = random.Random(seed)
        sentences = list(sentences)
        for _ in range(n_iter):
            rng.shuffle(sentences)
            for sent in sentences:
                tokens = [w for w, _ in sent]
                context = ([self.START[0], self.START[1]]
                           + [_normalize(t) for t in tokens]
                           + ["-END-", "-END2-"])
                prev, prev2 = self.START
                for i, (tok, gold) in enumerate(sent):
                    guess = self.tagdict.get(_normalize(tok))
                    if guess is None:
                        feats = self._features(i + 2, tok, context, prev, prev2)
                        scores = self._score(feats)
                        guess = max(self.classes,
                                    key=lambda t: (scores.get(t, 0.0), t))
                        instances += 1
                        if guess != gold:
                            for f in feats:
                                fw = self.weights.setdefault(f, {})
                                for tag, delta in ((gold, 1.0), (guess, -1.0)):
                                    key = (f, tag)
                                    # lazy averaging bookkeeping
                                    totals[key] += ((instances - tstamps[key])
                                                    * fw.get(tag, 0.0))
                                    tstamps[key] = instances
                                    fw[tag] = fw.get(tag, 0.0) + delta
                    prev2, prev = prev, guess
        # average
        for f, fw in self.weights.items():
            for tag, w in list(fw.items()):
                key = (f, tag)
                total = totals[key] + (instances - tstamps[key]) * w
                fw[tag] = total / max(1, instances)

    def _make_tagdict(self, sentences, freq_thresh=5, ambiguity=0.99):
        counts: dict[str, dict[str, int]] = defaultdict(lambda: defaultdict(int))
        for sent in sentences:
            for w, t in sent:
                counts[_normalize(w)][t] += 1
        for w, tags in counts.items():
            tag, mode = max(tags.items(), key=lambda kv: kv[1])
            n = sum(tags.values())
            if n >= freq_thresh and mode / n >= ambiguity:
                self.tagdict[w] = tag

    # -- persistence / default model ------------------------------------
    @classmethod
    def default(cls) -> "AveragedPerceptronTagger":
        """Tagger trained on the vendored sample corpus (offline)."""
        tagger = cls()
        tagger.train(load_tagged_corpus(_DATA / "pos_sample.txt"))
        return tagger


def load_tagged_corpus(path) -> list[list[tuple[str, str]]]:
    """word/TAG format, one sentence per line."""
    sentences = []
    for line in Path(path).read_text().splitlines():
        if not line.strip():
            continue
        pairs = []
        for item in line.split():
            word, _, tag = item.rpartition("/")
            pairs.append((word, tag))
        sentences.append(pairs)
    return sentences


def pos_tag_viterbi(tokens: list[str], tagger: AveragedPerceptronTagger,
                    transition_prob: float | None = None) -> list[tuple[str, str]]:
    """Viterbi-smoothed tagging: the tagger's per-token emission scores
    decoded with ``utils.viterbi`` (the reference pipes PoS output into
    ``Viterbi.java`` the same way, via window labels).

    Default transitions are uniform: unlike the sticky window labels
    Viterbi smooths in the reference, POS tags rarely self-repeat, so a
    self-transition prior would hurt — pass ``transition_prob`` to bias."""
    from ..utils.viterbi import Viterbi
    if transition_prob is None:
        transition_prob = 1.0 / max(1, len(tagger.classes))
    probs = tagger.emissions(tokens)
    labels = Viterbi(tagger.classes, transition_prob).decode(probs)
    return list(zip(tokens, labels))


def tagged_windows(tokens: list[str], tagger: AveragedPerceptronTagger,
                   window_size: int = 5):
    """Labeled context windows: each window's label is the focus token's
    POS tag — the ``Windows``/``WindowConverter`` training-pair flow
    (``text/movingwindow/Windows.java:17``) with a real upstream tagger."""
    from .windows import windows as make_windows
    tags = (t for _, t in tagger.tag(tokens))
    return list(zip(make_windows(tokens, window_size), tags))
