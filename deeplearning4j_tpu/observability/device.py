"""Device-memory gauges.

Samples ``jax.local_devices()[*].memory_stats()`` into the metrics
registry.  TPU/GPU backends report ``bytes_in_use`` / ``peak_bytes_in_use``
/ ``bytes_limit``; the CPU backend returns ``None`` — sampling is then a
no-op, so instrumented paths can call this unconditionally.
"""

from __future__ import annotations

from . import core
from .metrics import METRICS, MetricsRegistry

_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")


def sample_device_memory(registry: MetricsRegistry = METRICS) -> int:
    """Gauge per-device memory stats; returns how many devices reported."""
    if not core.enabled():
        return 0
    try:
        import jax
        devices = jax.local_devices()
    except Exception:
        return 0
    reported = 0
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        reported += 1
        prefix = f"device.{d.id}."
        for k in _KEYS:
            if k in stats:
                registry.gauge(prefix + k, float(stats[k]))
    return reported
