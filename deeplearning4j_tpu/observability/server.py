"""HTTP status/metrics endpoint.

Read-only ThreadingHTTPServer (replaces the reference's dropwizard REST
resource, ``StateTrackerDropWizardResource.java:28``) serving:

- ``/healthz``       — liveness probe, ``{"ok": true}``
- ``/metrics``       — JSON registry snapshot (counters/gauges/timer summaries)
- ``/metrics.prom``  — Prometheus text exposition format (scrape target)
- ``/status``        — StateTracker state (workers/heartbeats/jobs/...)

``/status`` is defensive: a tracker whose worker disappears mid-snapshot
(eviction racing the enumerate) yields a partial status with an ``errors``
list, never a 500.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

from .metrics import METRICS, MetricsRegistry


class StatusServer:
    """REST endpoint over a metrics registry + optional StateTracker."""

    def __init__(self, tracker=None, registry: MetricsRegistry = METRICS,
                 host: str = "127.0.0.1", port: int = 0):
        self.tracker = tracker
        self.registry = registry
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, body: bytes, content_type: str) -> None:
                self.send_response(200)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    payload = {"ok": True}
                elif self.path == "/metrics":
                    payload = outer.registry.snapshot()
                elif self.path == "/metrics.prom":
                    self._send(outer.registry.to_prometheus().encode(),
                               "text/plain; version=0.0.4; charset=utf-8")
                    return
                elif self.path == "/status":
                    payload = outer._tracker_state()
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self._send(json.dumps(payload).encode(), "application/json")

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    def _tracker_state(self) -> dict:
        """Tracker snapshot tolerant of concurrent worker eviction: each
        field is gathered independently and per-worker lookups that raise
        (worker gone between ``workers()`` and the lookup) are skipped —
        the endpoint returns whatever it could read plus an ``errors``
        list, never a 500."""
        t = self.tracker
        if t is None:
            return {}
        state: dict[str, Any] = {}
        errors: list[str] = []

        def _get(key, fn):
            try:
                state[key] = fn()
            except Exception as e:  # partial status beats a 500
                errors.append(f"{key}: {type(e).__name__}: {e}")

        _get("workers", t.workers)
        workers = state.get("workers", [])

        def _per_worker(fn):
            out = {}
            for w in workers:
                try:
                    out[w] = fn(w)
                except Exception as e:
                    errors.append(f"{w}: {type(e).__name__}: {e}")
            return out

        _get("enabled", lambda: _per_worker(t.is_enabled))
        _get("heartbeats_age_s",
             lambda: _per_worker(lambda w: round(time.time() - t.last_heartbeat(w), 3)))
        _get("current_jobs", lambda: len(t.current_jobs()))
        _get("pending_updates", lambda: sorted(t.updates().keys()))
        # in-memory tracker exposes its counter dict; the file-backed
        # tracker has no cheap enumerate — omit rather than scan disk
        _get("counters", lambda: dict(getattr(t, "_counters", {})))
        _get("done", t.is_done)
        if errors:
            state["errors"] = errors
        return state

    def start(self) -> "StatusServer":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
