"""Driver (single-controller entry point) — names the reference's
Spark-driver/YARN-master role (VERDICT r3 coverage row 50) — plus the
inverted index's new disk persistence (row 61)."""

import json
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.optimize import transforms as T
from deeplearning4j_tpu.parallel.driver import Driver
from deeplearning4j_tpu.parallel.mesh import MeshSpec


class _Batch:
    def __init__(self, x, y):
        self.features, self.labels = x, y


def _problem():
    w_true = jnp.asarray([1.0, -2.0, 0.5])
    x = jax.random.normal(jax.random.key(0), (64, 3))
    y = x @ w_true
    params = {"w": jnp.zeros(3)}

    def loss_fn(p, xb, yb, key=None):
        return jnp.mean((xb @ p["w"] - yb) ** 2)

    batches = [_Batch(x[i * 8:(i + 1) * 8], y[i * 8:(i + 1) * 8])
               for i in range(8)]
    return params, loss_fn, batches, w_true


def test_driver_trains_checkpoints_and_serves_status(tmp_path):
    params, loss_fn, batches, w_true = _problem()
    driver = Driver(loss_fn, T.chain(T.momentum(0.9), T.sgd_lr(5e-2)),
                    mesh_spec=MeshSpec(dp=8),
                    checkpoint_dir=tmp_path / "ckpt", checkpoint_every=4,
                    status_port=0)
    try:
        state, losses = driver.run(params, batches, epochs=10)
        assert losses[-1] < losses[0] * 0.1
        w = np.asarray(driver.final_params(state)["w"])
        np.testing.assert_allclose(w, np.asarray(w_true), atol=0.2)
        assert driver.checkpoint_manager.latest_step() is not None
        # observability wired through
        metrics = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{driver.status_server.port}/metrics",
            timeout=10).read())
        assert metrics["counters"]["driver.steps"] >= len(losses)
    finally:
        driver.close()


def test_driver_resumes_from_checkpoint(tmp_path):
    params, loss_fn, batches, _ = _problem()
    tx = T.chain(T.momentum(0.9), T.sgd_lr(5e-2))

    d1 = Driver(loss_fn, tx, mesh_spec=MeshSpec(dp=8),
                checkpoint_dir=tmp_path / "ckpt", checkpoint_every=2)
    s1, _ = d1.run(params, batches, epochs=1)

    # a fresh driver process resumes at the saved step, not from scratch
    d2 = Driver(loss_fn, tx, mesh_spec=MeshSpec(dp=8),
                checkpoint_dir=tmp_path / "ckpt")
    s2, losses2 = d2.run(params, batches, epochs=1)
    assert s2.step == s1.step
    assert losses2 == []             # nothing left to do at the same epoch count


def test_inverted_index_save_load(tmp_path):
    from deeplearning4j_tpu.text.index import InvertedIndex

    idx = InvertedIndex()
    idx.add_doc("the quick brown fox", label="a")
    idx.add_doc("the lazy dog", label="b")
    idx.save(tmp_path / "corpus.idx.gz")

    idx2 = InvertedIndex.load(tmp_path / "corpus.idx.gz")
    assert idx2.num_documents() == 2
    assert idx2.label(1) == "b"
    assert idx2.documents_for("the") == [0, 1]
    assert idx2.search("quick fox")[0][0] == 0
    assert idx2.all_docs() == idx.all_docs()
