"""Goodput accounting: where did the wall-clock go?

The resilience tier can survive divergence, chip loss, and preemption —
but surviving costs time, and nothing measured it.  A
:class:`GoodputTracker` classifies a supervised run's wall-clock into
exhaustive, non-overlapping states:

- ``productive`` — the trainer is dispatching/resolving real steps
- ``checkpoint`` — saving (fence + serialize + fsync)
- ``restore`` — restoring or resharding state (includes elastic resize)
- ``rollback`` — divergence rollback + retry backoff sleeps
- ``stall`` — the device was idle waiting on host data
- ``drain`` — cooperative stop/preemption drain (emergency checkpoint
  window between the stop signal and the run actually ending)

The tracker is an interval state machine, not a span scraper: every
``transition()`` closes the current interval at the moment the next one
opens, so the per-state seconds are contiguous by construction and sum to
wall-clock *exactly* — the chaos smoke asserts this within 1% against its
own independent clock.  ``goodput.fraction`` is the productive share.

Single-threaded by contract: the supervisor and the trainer it drives
mutate the tracker from the same thread (the fit loop), so there is no
lock — readers from other threads (the time-series sampler) only see the
published gauges.

Owned by :class:`~..resilience.supervisor.TrainingSupervisor` (created
only while observability is enabled) and threaded into
``DataParallelTrainer.fit(goodput=...)``; a bare trainer run can attach
one explicitly the same way ``chaos_smoke`` does.
"""

from __future__ import annotations

import time
from typing import Any

from .metrics import METRICS, MetricsRegistry

# The exhaustive state set — DESIGN.md §22 documents the transition map.
STATES: tuple[str, ...] = (
    "productive", "checkpoint", "restore", "rollback", "stall", "drain")

# A data-fetch wait shorter than this is attributed to ``productive``:
# sub-millisecond queue pops are pipeline noise, not a stall, and
# materializing them would bloat the timeline without moving the fraction.
STALL_THRESHOLD_S = 0.005

# Coalesced interval entries kept for exact-sequence tests and bundles;
# the per-state seconds stay exact regardless of this cap.
TIMELINE_CAP = 1024


class _Phase:
    """``with tracker.phase("checkpoint"):`` — enter the state for the
    body, return to the interrupted state on exit."""

    __slots__ = ("tracker", "state", "prev")

    def __init__(self, tracker: "GoodputTracker", state: str):
        self.tracker = tracker
        self.state = state

    def __enter__(self):
        self.prev = self.tracker.state
        self.tracker.transition(self.state)
        return self

    def __exit__(self, *exc):
        self.tracker.transition(self.prev)
        return False


class GoodputTracker:
    """Classifies wall-clock into the :data:`STATES` intervals."""

    def __init__(self, registry: MetricsRegistry = METRICS,
                 stall_threshold_s: float = STALL_THRESHOLD_S,
                 timeline_cap: int = TIMELINE_CAP):
        self.registry = registry
        self.stall_threshold_s = float(stall_threshold_s)
        self.timeline_cap = int(timeline_cap)
        now = time.perf_counter()
        self.started_at = now
        self.state = "productive"
        self._t0 = now
        self.seconds: dict[str, float] = {s: 0.0 for s in STATES}
        # Coalesced (state, t0, dur) intervals, offsets relative to start.
        self.timeline: list[list[Any]] = []
        self.timeline_dropped = 0
        self.finished = False
        self._end: float | None = None

    # ------------------------------------------------------------ intervals
    def _close(self, t: float) -> None:
        dur = max(0.0, t - self._t0)
        self.seconds[self.state] += dur
        if dur > 0.0:
            rel = self._t0 - self.started_at
            if self.timeline and self.timeline[-1][0] == self.state:
                self.timeline[-1][2] += dur
            elif len(self.timeline) >= self.timeline_cap:
                self.timeline_dropped += 1
            else:
                self.timeline.append([self.state, rel, dur])

    def transition(self, state: str, t: float | None = None) -> None:
        """Close the current interval and open ``state`` at ``t`` (now by
        default).  ``t`` may not precede the current interval's start."""
        if self.finished:
            return
        if state not in self.seconds:
            raise ValueError(f"unknown goodput state {state!r}")
        if t is None:
            t = time.perf_counter()
        t = max(t, self._t0)
        self._close(t)
        self.state = state
        self._t0 = t

    def phase(self, state: str) -> _Phase:
        """Context manager: ``state`` for the body, previous state after."""
        return _Phase(self, state)

    def data_wait(self, t0: float, t1: float) -> None:
        """Attribute a measured host-data wait ``[t0, t1]`` (perf_counter
        seconds).  Waits under the threshold stay ``productive``; longer
        ones are carved out as a ``stall`` interval in place."""
        if self.finished or t1 - t0 < self.stall_threshold_s:
            return
        prev = self.state
        self.transition("stall", t0)
        self.transition(prev, t1)

    # ------------------------------------------------------------- results
    def wall_seconds(self, t: float | None = None) -> float:
        end = self._end if self._end is not None else (
            t if t is not None else time.perf_counter())
        return max(0.0, end - self.started_at)

    def fraction(self) -> float:
        """Productive share of wall-clock so far (1.0 for an empty run)."""
        now = time.perf_counter()
        wall = self.wall_seconds(now)
        prod = self.seconds["productive"]
        if not self.finished and self.state == "productive":
            prod += max(0.0, now - self._t0)
        return prod / wall if wall > 0 else 1.0

    def state_sequence(self) -> list[str]:
        """The coalesced state order — what the fixed-seed tests assert."""
        return [entry[0] for entry in self.timeline]

    def finish(self, t: float | None = None) -> dict[str, Any]:
        """Close the final interval, publish gauges, return the report.

        Idempotent: a second call returns the same report without moving
        the clock.
        """
        if not self.finished:
            if t is None:
                t = time.perf_counter()
            t = max(t, self._t0)
            self._close(t)
            self._end = t
            self.finished = True
            self.publish()
        return self.report()

    def publish(self) -> None:
        """Push ``goodput.fraction`` + per-state seconds gauges (also safe
        mid-run, where the open interval counts up to now)."""
        wall = self.wall_seconds()
        frac = self.fraction() if wall > 0 else 1.0
        self.registry.gauge("goodput.fraction", frac)
        self.registry.gauge("goodput.wall_seconds", wall)
        for s in STATES:
            self.registry.gauge(f"goodput.seconds.{s}", self.seconds[s])

    def report(self) -> dict[str, Any]:
        wall = self.wall_seconds()
        accounted = sum(self.seconds.values())
        return {
            "wall_seconds": wall,
            "accounted_seconds": accounted,
            "fraction": (self.seconds["productive"] / wall) if wall > 0 else 1.0,
            "seconds": dict(self.seconds),
            "timeline": [tuple(e) for e in self.timeline],
            "timeline_dropped": self.timeline_dropped,
            "states": self.state_sequence(),
        }
