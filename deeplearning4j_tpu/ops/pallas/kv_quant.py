"""KV-cache quantization helpers: per-page, per-head absmax scales.

The paged serving tier (DESIGN.md §17) stores K/V as fixed-size pages in
a shared device pool; this module extends the ``matmul_int8`` symmetric
absmax machinery from weight precision to CACHE precision (DESIGN.md
§20).  Storage is int8 (or, gated off by default, fp8) with one f32
scale per (page, kv_head): ``scale[p, h] = max(|page[p, :, h, :]|) /
qmax``, so dequantization inside the paged-attention read is one
broadcast multiply per page — the shape the streamed Pallas kernel DMAs
anyway.

Write discipline (the part that makes incremental decode sound): scales
are MONOTONE per page — ``requantize_pool`` takes ``max(old_scale,
amax/qmax)`` — so a page whose content did not change requantizes to
byte-identical storage (``round(q * s / s) == q``), and repeated
single-token writes can never drift the untouched remainder of the
pool.  A freed page's scale resets to :func:`neutral_scale` (wipe
hygiene in ``reset_cache_pages``), so a previous occupant's large scale
cannot poison the next sequence's precision.

Every raw precision cast lives HERE (``cast_to``): graftlint QT01 keeps
``serving/`` and ``models/`` free of ad-hoc ``.astype(jnp.int8)`` /
``.astype(jnp.float8_*)`` so scale handling stays centralized.
"""

from __future__ import annotations

import jax.numpy as jnp

#: fp8 storage rides the same seam as int8 but only exists when the
#: installed jax exposes float8_e4m3fn — and is gated off by default
#: either way (adoption goes through the bench autopick agreement gate)
_FP8 = getattr(jnp, "float8_e4m3fn", None)

#: kv_quant modes ServingConfig accepts on this build
KV_QUANT_MODES = ("int8",) + (("fp8",) if _FP8 is not None else ())


def storage_dtype(mode: str):
    """The on-device dtype of a quantized KV page for ``mode``."""
    if mode == "int8":
        return jnp.int8
    if mode == "fp8":
        if _FP8 is None:
            raise ValueError(
                "kv_quant='fp8' needs a jax build with float8_e4m3fn")
        return _FP8
    raise ValueError(
        f"unknown kv_quant mode {mode!r} (supported: {KV_QUANT_MODES})")


def qmax(dtype) -> float:
    """Largest magnitude the absmax scale maps onto for ``dtype``."""
    d = jnp.dtype(dtype)
    if d == jnp.dtype(jnp.int8):
        return 127.0
    if _FP8 is not None and d == jnp.dtype(_FP8):
        return 448.0  # float8_e4m3fn finite max
    raise ValueError(f"not a KV storage dtype: {dtype!r}")


def neutral_scale(dtype) -> float:
    """Scale of an all-zero (freshly wiped) page: positive so dequant is
    division-safe, and MINIMAL so the monotone per-page running max only
    grows from real content, never from a stale previous occupant."""
    return 1.0 / qmax(dtype)


def cast_to(x, dtype):
    """Saturating cast of already-scaled f32 values into the storage
    dtype — the one place a raw KV precision cast is allowed (QT01)."""
    m = qmax(dtype)
    x = jnp.clip(x, -m, m)
    if jnp.dtype(dtype) == jnp.dtype(jnp.int8):
        x = jnp.round(x)
    return x.astype(dtype)


def init_quantized_paged_cache(cfg, num_pages: int, page_size: int,
                               mode: str) -> list:
    """Quantized twin of ``transformer.init_paged_cache``: per-layer
    int8/fp8 K/V pools ``(num_pages, page_size, n_kv_heads, Dh)`` plus
    ``(num_pages, n_kv_heads)`` f32 per-page per-head scales for each of
    k and v.  Key presence (``k_scale``) is how every consumer detects a
    quantized pool — the same static-dispatch idiom as ``w1_q``."""
    dt = storage_dtype(mode)
    kvh = cfg.kv_heads
    shape = (num_pages, page_size, kvh, cfg.head_dim)

    def s0():
        # fresh array per leaf: the engine DONATES its decode state, and
        # XLA rejects the same buffer appearing at two donated positions
        return jnp.full((num_pages, kvh), neutral_scale(dt), jnp.float32)

    return [{"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt),
             "k_scale": s0(), "v_scale": s0()}
            for _ in range(cfg.n_layers)]


def dequantize_pool(q, scale, dtype=jnp.float32):
    """``(P, ps, K, Dh)`` storage × ``(P, K)`` scales → ``dtype`` pool."""
    return (q.astype(jnp.float32) * scale[:, None, :, None]).astype(dtype)


def requantize_pool(f, scale, dtype):
    """Quantize a float pool back into storage against monotone per-page
    per-head absmax scales.  ``scale`` is the pool's CURRENT scale tree;
    the new scale is ``max(scale, amax/qmax)``, so pages whose content
    did not change round-trip byte-identically (see module docstring).
    Returns ``(storage pool, new scales)``."""
    f32 = f.astype(jnp.float32)
    amax = jnp.max(jnp.abs(f32), axis=(1, 3))
    s = jnp.maximum(scale, amax / qmax(dtype))
    return cast_to(f32 / s[:, None, :, None], dtype), s


def kv_itemsize(mode: str | None, model_dtype) -> int:
    """Bytes per stored K/V element under ``mode`` (None = full
    precision at the model's dtype) — the gauge layer's accounting."""
    if mode is None:
        return jnp.dtype(model_dtype).itemsize
    return jnp.dtype(storage_dtype(mode)).itemsize
