"""Time-series telemetry: the registry, sampled on a clock.

The metrics layer is snapshot-only — every gauge is a point-in-time read,
so anything that happens *between* scrapes (a TTFT spike, a goodput dip, a
burn-rate breach) is invisible.  :class:`TimeSeriesStore` closes that gap:
a background daemon thread samples every registered counter, gauge, and
histogram quantile at a fixed interval into a bounded ring per series, and
appends each sample row to a JSONL file under ``DL4J_TPU_TS_DIR`` so the
history survives the process (``tools/metrics_dump.py --timeline`` reads
it back).

Contracts:

- **Disabled is free** (DESIGN.md §9): ``start()`` refuses to spawn a
  thread while observability is off, and ``sample_once()`` returns before
  touching any lock — no thread, no allocation, no file.
- **Lockguard-clean**: the registry snapshot is taken *before* the store
  lock so the two locks never nest, and evaluators (the SLO tier) run
  after the store lock is released.
- **Bounded**: each series keeps at most ``ring`` points; evictions are
  counted per series (``dropped`` in :meth:`stats`) rather than silently
  forgotten.  The JSONL file is append-only and unbounded by design —
  retention is the operator's cron job, not ours (DESIGN.md §22).
- **Torn tails tolerated**: :func:`read_back` skips a truncated final
  line, so a sampler killed mid-write never poisons the reader.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any, Callable, Iterable

from . import core
from .metrics import METRICS, MetricsRegistry

ENV_TS_DIR = "DL4J_TPU_TS_DIR"

# Histogram quantiles sampled per timer series, as (suffix, summary key).
_QUANTILES: tuple[tuple[str, str], ...] = (
    ("p50", "p50_s"), ("p95", "p95_s"), ("p99", "p99_s"))


class TimeSeriesStore:
    """Samples a :class:`MetricsRegistry` into per-series bounded rings.

    Series names are the registry names, with histogram quantiles exposed
    as ``<timer>.p50`` / ``.p95`` / ``.p99``.  Counters are sampled as
    their cumulative value (rates are a reader-side diff).
    """

    def __init__(self, registry: MetricsRegistry = METRICS,
                 interval_s: float = 1.0, ring: int = 512,
                 out_dir: str | os.PathLike | None = None):
        self.registry = registry
        self.interval_s = float(interval_s)
        self.ring = int(ring)
        self._lock = threading.Lock()
        self._series: dict[str, deque[tuple[float, float]]] = {}
        self._dropped: dict[str, int] = {}
        self._samples = 0
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        # Called as fn(store, t) after each sample, outside the store lock
        # (the SLO evaluator hangs off this hook — scrape-free path).
        self._evaluators: list[Callable[["TimeSeriesStore", float], None]] = []
        d = out_dir if out_dir is not None else os.environ.get(ENV_TS_DIR)
        self.out_path: Path | None = None
        if d:
            p = Path(d)
            p.mkdir(parents=True, exist_ok=True)
            self.out_path = p / f"timeseries-{os.getpid()}.jsonl"

    # ------------------------------------------------------------ lifecycle
    def start(self) -> bool:
        """Spawn the sampler daemon.  Returns False (and spawns nothing)
        when observability is disabled or the thread is already running."""
        if not core.enabled():
            return False
        if self._thread is not None and self._thread.is_alive():
            return False
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="dl4j-tpu-timeseries", daemon=True)
        self._thread.start()
        return True

    def stop(self, timeout_s: float = 5.0) -> None:
        t = self._thread
        self._thread = None
        if t is None:
            return
        self._stop.set()
        t.join(timeout=timeout_s)

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:
                pass  # a sampler must never take the process down

    # ------------------------------------------------------------- sampling
    def add_evaluator(self, fn: Callable[["TimeSeriesStore", float], None]) -> None:
        self._evaluators.append(fn)

    def sample_once(self, t: float | None = None) -> int:
        """Take one sample of every registered series.  Returns the number
        of series sampled (0 while disabled — and no work was done)."""
        if not core.enabled():
            return 0
        snap = self.registry.snapshot()  # registry lock; released before ours
        if t is None:
            t = time.time()
        row: dict[str, float] = {}
        for name, v in snap["counters"].items():
            row[name] = float(v)
        for name, v in snap["gauges"].items():
            row[name] = float(v)
        for name, summ in snap["timers"].items():
            for suffix, key in _QUANTILES:
                v = summ[key]
                if v == v:  # skip NaN quantiles (empty window)
                    row[f"{name}.{suffix}"] = float(v)
        with self._lock:
            self._samples += 1
            for name, v in row.items():
                ring = self._series.get(name)
                if ring is None:
                    ring = self._series[name] = deque(maxlen=self.ring)
                if len(ring) == self.ring:
                    self._dropped[name] = self._dropped.get(name, 0) + 1
                ring.append((t, v))
        if self.out_path is not None and row:
            try:
                with open(self.out_path, "a") as f:
                    f.write(json.dumps({"t": t, "series": row}) + "\n")
            except OSError:
                pass
        for fn in self._evaluators:
            try:
                fn(self, t)
            except Exception:
                pass
        return len(row)

    # -------------------------------------------------------------- reading
    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._series)

    def series(self, name: str) -> list[tuple[float, float]]:
        """Ring contents for one series, oldest first (copy)."""
        with self._lock:
            ring = self._series.get(name)
            return list(ring) if ring else []

    def last(self, name: str) -> float | None:
        with self._lock:
            ring = self._series.get(name)
            return ring[-1][1] if ring else None

    def window(self, name: str, seconds: float,
               now: float | None = None) -> list[tuple[float, float]]:
        """Points within the trailing ``seconds`` of ``now``."""
        pts = self.series(name)
        if not pts:
            return []
        if now is None:
            now = pts[-1][0]
        lo = now - seconds
        return [(t, v) for t, v in pts if t >= lo]

    def trend(self, name: str, window_s: float,
              now: float | None = None) -> tuple[float, float, int] | None:
        """Least-squares line fit over the trailing ``window_s`` of one
        series: ``(slope_per_second, r_squared, n_samples)``, or ``None``
        with fewer than two points (or zero time spread).  A perfectly
        flat series fits its own flat line exactly (slope 0, R² 1) — the
        forecast tier reads that as "never breaching", not "no data".
        """
        pts = self.window(name, window_s, now)
        n = len(pts)
        if n < 2:
            return None
        mt = sum(t for t, _ in pts) / n
        mv = sum(v for _, v in pts) / n
        sxx = sum((t - mt) ** 2 for t, _ in pts)
        if sxx <= 0.0:
            return None  # all points at one instant: slope undefined
        sxy = sum((t - mt) * (v - mv) for t, v in pts)
        syy = sum((v - mv) ** 2 for _, v in pts)
        slope = sxy / sxx
        r2 = (sxy * sxy) / (sxx * syy) if syy > 0.0 else 1.0
        return slope, r2, n

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "samples": self._samples,
                "series": len(self._series),
                "dropped": dict(self._dropped),
                "dropped_total": sum(self._dropped.values()),
            }

    def reset(self) -> None:
        with self._lock:
            self._series.clear()
            self._dropped.clear()
            self._samples = 0


def read_back(path: str | os.PathLike) -> list[dict[str, Any]]:
    """Read a time-series JSONL file, tolerating a torn final line (the
    sampler may have been killed mid-append).  A torn line anywhere else
    is also skipped — readers want the history, not an exception."""
    rows: list[dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if isinstance(row, dict) and "series" in row:
                    rows.append(row)
    except OSError:
        return []
    return rows


def read_back_series(paths: Iterable[str | os.PathLike]) -> dict[str, list[tuple[float, float]]]:
    """Merge one or more JSONL files into ``{name: [(t, value), ...]}``
    sorted by time — the shape ``metrics_dump --timeline`` renders."""
    out: dict[str, list[tuple[float, float]]] = {}
    for path in paths:
        for row in read_back(path):
            t = float(row.get("t", 0.0))
            for name, v in row["series"].items():
                try:
                    out.setdefault(name, []).append((t, float(v)))
                except (TypeError, ValueError):
                    continue
    for pts in out.values():
        pts.sort(key=lambda p: p[0])
    return out
