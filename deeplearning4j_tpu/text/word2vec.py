"""Word2Vec — skip-gram with hierarchical softmax and negative sampling,
batched on the TPU.

Capability match of the reference's ``models/word2vec/Word2Vec.java`` +
``models/embeddings/inmemory/InMemoryLookupTable.java:144-279``: vocab build
with min-frequency pruning, Huffman tree, skip-gram windows, hierarchical
softmax over the Huffman path, negative sampling from the 0.75-power unigram
table, subsampling, linear LR decay by words processed
(``Word2VecPerformer.java:82``), similarity/nearest-neighbor queries, and
(de)serialization via ``serializer``.

TPU-first redesign: the reference updates one (w1, w2) pair at a time with
BLAS ``axpy`` on host; here the host assembles BATCHES of (center, context,
padded Huffman path) index arrays and one jitted step performs all updates
as gathers + scatter-adds — MXU-friendly, thousands of pairs per dispatch.
The precomputed sigmoid expTable is unnecessary (XLA fuses the exact
sigmoid); the unigram table becomes a device-side categorical draw.
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..observability import METRICS, trace
from .sentence import CollectionSentenceIterator
from .tokenization import CommonPreprocessor, DefaultTokenizerFactory
from .vocab import Huffman, VocabCache, build_vocab

log = logging.getLogger(__name__)


# --------------------------------------------------------------------------- jitted steps

@partial(jax.jit, donate_argnums=(0, 1))
def _hs_step(syn0, syn1, centers, points, codes, mask, alpha):
    """Hierarchical-softmax skip-gram update for a batch of pairs.

    centers: (B,) int; points/codes/mask: (B, L) Huffman path arrays.
    label = 1 - code (word2vec convention); in-place adds via scatter.
    """
    h = syn0[centers]                                  # (B, D)
    w = syn1[points]                                   # (B, L, D)
    u = jnp.einsum("bd,bld->bl", h, w)
    p = jax.nn.sigmoid(u)
    g = (1.0 - codes - p) * alpha * mask               # (B, L)
    dh = jnp.einsum("bl,bld->bd", g, w)
    dw = g[:, :, None] * h[:, None, :]
    syn1 = syn1.at[points].add(dw)
    syn0 = syn0.at[centers].add(dh)
    return syn0, syn1


@partial(jax.jit, donate_argnums=(0, 1))
def _ns_step(syn0, syn1neg, centers, targets, labels, alpha):
    """Negative-sampling update.

    centers: (B,); targets: (B, 1+K) (context + K negatives);
    labels: (B, 1+K) 1 for context, 0 for negatives.
    """
    h = syn0[centers]
    w = syn1neg[targets]                               # (B, 1+K, D)
    u = jnp.einsum("bd,bkd->bk", h, w)
    p = jax.nn.sigmoid(u)
    g = (labels - p) * alpha
    dh = jnp.einsum("bk,bkd->bd", g, w)
    dw = g[:, :, None] * h[:, None, :]
    syn1neg = syn1neg.at[targets].add(dw)
    syn0 = syn0.at[centers].add(dh)
    return syn0, syn1neg


@partial(jax.jit, static_argnums=(2,))
def _sample_negatives(key, probs_log, shape):
    return jax.random.categorical(key, probs_log, shape=shape)


# --------------------------------------------------------------------------- pair generation

def skipgram_pairs(sentences_idx: Sequence[np.ndarray], window: int,
                   rng: np.random.Generator) -> tuple[np.ndarray, np.ndarray]:
    """All (center, context) skip-gram pairs with random window shrink
    (the reference draws a random gap per position, Word2Vec.java:312).
    Native C++ fast path when the host library is built."""
    try:
        from ..native import runtime as native_rt
        native = native_rt.skipgram_pairs(
            list(sentences_idx), window, int(rng.integers(1, 2**63)))
        if native is not None:
            return native
    except ImportError:
        pass
    centers, contexts = [], []
    for idx in sentences_idx:
        n = idx.size
        b = rng.integers(0, window, n)  # random reduced window
        for pos in range(n):
            w = window - b[pos]
            lo, hi = max(0, pos - w), min(n, pos + w + 1)
            for j in range(lo, hi):
                if j != pos:
                    centers.append(idx[pos])
                    contexts.append(idx[j])
    if not centers:
        return np.zeros(0, np.int32), np.zeros(0, np.int32)
    return np.asarray(centers, np.int32), np.asarray(contexts, np.int32)


# --------------------------------------------------------------------------- model

class Word2Vec:
    """Skip-gram embeddings with the reference's knobs."""

    def __init__(self, sentences: Iterable[str] | None = None, *,
                 layer_size: int = 100, window: int = 5,
                 min_word_frequency: float = 1.0, iterations: int = 1,
                 learning_rate: float = 0.025, min_learning_rate: float = 1e-2,
                 negative: int = 0, use_hierarchic_softmax: bool = True,
                 sample: float = 0.0, batch_size: int = 4096,
                 seed: int = 42, tokenizer_factory=None):
        self.sentences = list(sentences) if sentences is not None else []
        self.layer_size = layer_size
        self.window = window
        self.min_word_frequency = min_word_frequency
        self.iterations = iterations
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.negative = negative
        self.use_hs = use_hierarchic_softmax or negative == 0
        self.sample = sample
        self.batch_size = batch_size
        self.seed = seed
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory(
            CommonPreprocessor())

        self.vocab: VocabCache | None = None
        self.huffman: Huffman | None = None
        self.syn0 = None
        self.syn1 = None
        self.syn1neg = None
        self._codes = self._points = self._lengths = None
        self._unigram_log = None

    # ------------------------------------------------------------------ setup
    def build_vocab(self) -> None:
        self.vocab = build_vocab(self.sentences, self.tokenizer_factory,
                                 self.min_word_frequency)
        self.huffman = Huffman(self.vocab)
        self.huffman.build()
        self._codes, self._points, self._lengths = self.huffman.code_arrays()

    def reset_weights(self) -> None:
        """syn0 uniform +-0.5/dim, syn1 zeros (InMemoryLookupTable
        ``resetWeights``)."""
        n, d = len(self.vocab), self.layer_size
        rng = np.random.default_rng(self.seed)
        self.syn0 = jnp.asarray(
            (rng.random((n, d), np.float32) - 0.5) / d)
        self.syn1 = jnp.zeros((max(n - 1, 1), d), jnp.float32)
        if self.negative > 0:
            self.syn1neg = jnp.zeros((n, d), jnp.float32)
            counts = self.vocab.counts_array() ** 0.75
            self._unigram_log = jnp.asarray(
                np.log(counts / counts.sum()), dtype=jnp.float32)

    # ------------------------------------------------------------------ data
    def _sentence_indices(self, rng: np.random.Generator) -> list[np.ndarray]:
        """Tokenize sentences to pruned index arrays, with subsampling."""
        out = []
        total = self.vocab.total_word_count
        counts = self.vocab.counts_array() if self.sample > 0 else None
        for s in self.sentences:
            toks = self.tokenizer_factory.create(s).get_tokens()
            idx = [self.vocab.index_of(t) for t in toks]
            idx = np.array([i for i in idx if i >= 0], np.int32)
            if self.sample > 0 and idx.size:
                freqs = counts[idx] / total
                keep_p = np.minimum(1.0, np.sqrt(self.sample / freqs)
                                    + self.sample / freqs)
                idx = idx[rng.random(idx.size) < keep_p]
            if idx.size >= 2:
                out.append(idx)
        return out

    def _pairs(self, sentences_idx: Sequence[np.ndarray],
               rng: np.random.Generator) -> tuple[np.ndarray, np.ndarray]:
        return skipgram_pairs(sentences_idx, self.window, rng)

    # ------------------------------------------------------------------ step seams
    # (overridden by ShardedWord2Vec to run the same schedule over mesh-
    # sharded tables — the TPU-native Word2VecWork row-shipping equivalent)
    def _apply_hs(self, cb, pts, cds, msk, alpha):
        self.syn0, self.syn1 = _hs_step(self.syn0, self.syn1, cb, pts, cds,
                                        msk, alpha)

    def _apply_ns(self, cb, targets, labels, alpha):
        self.syn0, self.syn1neg = _ns_step(self.syn0, self.syn1neg, cb,
                                           targets, labels, alpha)

    @property
    def embeddings(self) -> np.ndarray:
        """(n_vocab, D) host array — trims any shard padding."""
        return np.asarray(self.syn0)[:len(self.vocab)]

    # ------------------------------------------------------------------ fit
    def fit(self) -> "Word2Vec":
        if self.vocab is None:
            self.build_vocab()
        if self.syn0 is None:
            self.reset_weights()
        rng = np.random.default_rng(self.seed)
        key = jax.random.key(self.seed)
        codes = jnp.asarray(self._codes, jnp.float32)
        points = jnp.asarray(self._points)
        L = self._codes.shape[1]
        mask_table = jnp.asarray(
            (np.arange(L)[None, :] < self._lengths[:, None]).astype(np.float32))

        # Linear alpha decay over total training PAIRS (the reference decays
        # by words seen, Word2VecPerformer.java:82; pairs are the unit our
        # batches process — estimated from the first epoch's pair count so
        # the schedule spans all of training instead of collapsing early).
        pairs_total = None
        pairs_seen = 0.0
        for it in range(self.iterations):
            sidx = self._sentence_indices(rng)
            centers, contexts = self._pairs(sidx, rng)
            n_pairs = centers.shape[0]
            if pairs_total is None:
                pairs_total = max(1.0, float(n_pairs) * self.iterations)
            perm = rng.permutation(n_pairs)
            centers, contexts = centers[perm], contexts[perm]
            with trace.span("word2vec.epoch", iteration=it,
                            pairs=int(n_pairs)):
                for off in range(0, n_pairs, self.batch_size):
                    cb = jnp.asarray(centers[off:off + self.batch_size])
                    xb = jnp.asarray(contexts[off:off + self.batch_size])
                    alpha = max(
                        self.min_learning_rate,
                        self.learning_rate * (1.0 - pairs_seen / pairs_total))
                    if self.use_hs:
                        self._apply_hs(cb, points[xb], codes[xb],
                                       mask_table[xb], jnp.float32(alpha))
                    if self.negative > 0:
                        key, sub = jax.random.split(key)
                        negs = _sample_negatives(
                            sub, self._unigram_log,
                            (cb.shape[0], self.negative))
                        targets = jnp.concatenate([xb[:, None], negs], axis=1)
                        labels = jnp.concatenate(
                            [jnp.ones((cb.shape[0], 1), jnp.float32),
                             jnp.zeros((cb.shape[0], self.negative),
                                       jnp.float32)],
                            axis=1)
                        self._apply_ns(cb, targets, labels, jnp.float32(alpha))
                    pairs_seen += cb.shape[0]
                    METRICS.increment("word2vec.batches")
        return self

    # ------------------------------------------------------------------ queries
    def get_word_vector(self, word: str) -> np.ndarray | None:
        i = self.vocab.index_of(word)
        return None if i < 0 else np.asarray(self.syn0[i])

    def has_word(self, word: str) -> bool:
        return self.vocab is not None and word in self.vocab

    def similarity(self, w1: str, w2: str) -> float:
        from .similarity import cosine
        return cosine(self.get_word_vector(w1), self.get_word_vector(w2))

    def words_nearest(self, word_or_vec, n: int = 10) -> list[str]:
        from .similarity import nearest
        if isinstance(word_or_vec, str):
            vec = self.get_word_vector(word_or_vec)
            exclude = {word_or_vec}
            if vec is None:
                return []
        else:
            vec, exclude = np.asarray(word_or_vec), set()
        return nearest(self.embeddings, vec, self.vocab.word_at, n, exclude)

    def accuracy(self, analogies: Sequence[tuple[str, str, str, str]]) -> float:
        """a:b :: c:d analogy accuracy (reference ``accuracy`` API)."""
        good = 0
        for a, b, c, d in analogies:
            va, vb, vc = (self.get_word_vector(w) for w in (a, b, c))
            if va is None or vb is None or vc is None:
                continue
            pred = self.words_nearest(vb - va + vc, n=4)
            if d in pred:
                good += 1
        return good / max(1, len(analogies))
