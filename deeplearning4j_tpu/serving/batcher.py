"""Request admission: bounded queue, deadlines, coalescing (DESIGN.md §13).

The serving front door.  Backpressure is explicit and cheap: the queue is
bounded and a full queue rejects AT SUBMIT TIME with :class:`QueueFull`
(the HTTP layer maps it to 429) instead of buffering unbounded work the
engine can never catch up on — the TensorFlow-Serving batching discipline
(PAPERS.md, Abadi et al. 2016).  Deadline-aware admission: a request whose
deadline expired while queued is dropped at admission time (it completes
exceptionally with :class:`DeadlineExceeded`) and never occupies a decode
slot — decoding tokens nobody will wait for is the most expensive way to
miss an SLO.  Coalescing: when the engine is idle, :meth:`RequestQueue.take`
holds the first arrival up to ``max_batch_delay_ms`` waiting for
companions, so the first device batch after an idle period dispatches
fuller (latency traded for fill ratio, bounded by the window).
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from collections import deque
from typing import Any

from ..observability import FLIGHTREC, METRICS, TENANTS
from ..resilience.faults import FAULTS


class ServingRejected(RuntimeError):
    """Base of the load-shedding rejections; ``status`` is the HTTP code
    the server layer answers with."""

    status = 503


class QueueFull(ServingRejected):
    """The bounded request queue is at capacity — back off and retry."""

    status = 429


class DeadlineExceeded(ServingRejected):
    """The request's deadline passed while it was still queued."""

    status = 504


class PagePoolExhausted(ServingRejected):
    """The paged KV pool cannot cover a new sequence even after evicting
    every unpinned prefix-cache entry — admission backpressure, not a
    crash: the request is rejected (HTTP 429) and in-flight slots keep
    decoding; retry when slots drain and their pages free."""

    status = 429


_REQ_IDS = itertools.count(1)

# priority tiers (GenerateRequest.priority / RequestQueue._tiers index)
INTERACTIVE = 0
BACKGROUND = 1


@dataclasses.dataclass
class GenerateRequest:
    """One autoregressive generation request (token-id space — tokenizers
    live outside this framework, as in ``Transformer.sample``)."""

    prompt: list[int]
    max_new_tokens: int
    temperature: float = 0.0        # <= 0 -> greedy, like Transformer.sample
    seed: int = 0                   # per-request RNG stream: jax.random.key(seed)
    eos_id: int | None = None       # evict the slot early on this token
    deadline_s: float | None = None  # absolute time.monotonic() deadline
    id: int = dataclasses.field(default_factory=lambda: next(_REQ_IDS))
    submitted_s: float = 0.0        # stamped by RequestQueue.submit
    # distributed-trace identity (stamped by InferenceEngine.submit when
    # observability is on; empty strings otherwise — zero extra allocation)
    trace_id: str = ""              # W3C trace id for the whole request
    parent_span_id: str = ""        # inbound traceparent's span (if any)
    root_span_id: str = ""          # the serving.request span's own id
    submitted_perf: float = 0.0     # perf_counter twin of submitted_s (spans)
    # bounded tenant label (stamped by InferenceEngine.submit through
    # TenantLabels.label — NEVER a raw request string; empty when the
    # request carries no tenant or observability is off)
    tenant: str = ""
    # priority tier: INTERACTIVE (0) or BACKGROUND (1) — background work
    # is claimed only when no interactive request waits, preempted back
    # into the queue at claim time, and shed first under brownout
    priority: int = 0


@dataclasses.dataclass
class ScoreRequest:
    """One row of a batched forward/score call (``BatchScorer``)."""

    x: Any
    deadline_s: float | None = None
    id: int = dataclasses.field(default_factory=lambda: next(_REQ_IDS))
    submitted_s: float = 0.0


@dataclasses.dataclass
class Completion:
    """Terminal result of a generation request.

    ``generation``/``loaded_step`` stamp the weight generation EVERY
    token of this completion was decoded under (DESIGN.md §23): the
    engine defers hot swaps to resolve fences with no request in flight,
    so a single response can never mix generations — its tokens equal
    the offline ``Transformer.sample`` of exactly that checkpoint."""

    tokens: list[int]
    finish_reason: str              # "eos" | "length"
    latency_s: float = 0.0
    ttft_s: float | None = None     # fence-granular time to first token
    generation: int = 0             # weight generation (monotonic per swap)
    loaded_step: int | None = None  # checkpoint step of that generation


class PendingResult:
    """Caller-facing handle for a submitted request: ``result()`` blocks
    until the engine completes (or fails) it.

    Completion is SINGLE-SHOT: ``_complete``/``_fail`` race each other by
    design (deadline expiry on the queue side vs. resolution on the
    engine side, engine shutdown vs. an in-flight eviction), so the first
    transition wins atomically and every later one is a no-op — a caller
    can never observe a 504 *and* a completion for the same request."""

    def __init__(self, request):
        self.request = request
        self._done = threading.Event()
        self._lock = threading.Lock()
        self._value: Any = None              # guarded-by: self._lock
        self._exc: BaseException | None = None  # guarded-by: self._lock

    # -- engine side ----------------------------------------------------
    def _complete(self, value) -> bool:
        """Resolve successfully; False when a rival transition won."""
        with self._lock:
            if self._done.is_set():
                return False
            self._value = value
            self._done.set()
        return True

    def _fail(self, exc: BaseException) -> bool:
        """Resolve exceptionally; False when a rival transition won."""
        with self._lock:
            if self._done.is_set():
                return False
            self._exc = exc
            self._done.set()
        return True

    # -- caller side ----------------------------------------------------
    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: float | None = None):
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.request.id} not completed within {timeout}s")
        if self._exc is not None:
            raise self._exc
        return self._value


class RequestQueue:
    """Bounded two-tier FIFO between submitters (HTTP handler threads,
    direct callers) and the single engine loop.

    Tier 0 (INTERACTIVE) is claimed ahead of tier 1 (BACKGROUND): a
    background request whose claim races an interactive arrival is
    preempted — pushed back to the head of its tier — so batch work
    never occupies the decode slot a latency-sensitive request is
    waiting on.  Starvation is bounded by aging: a background request
    older than ``aging_s`` is served ahead of newer interactive
    arrivals and cannot be preempted.

    Deadline expiry removes a request the moment ANY queue operation
    observes it dead — not only when a ``take()`` happens to pop it —
    so ``serving.queue.depth`` counts live work.  (Before this sweep,
    expired requests parked mid-queue inflated the gauge during
    bursts, which is exactly the signal the autoscaler scales on.)
    """

    def __init__(self, max_depth: int = 64, max_batch_delay_ms: float = 2.0,
                 aging_s: float = 2.0,
                 depth_gauge: str = "serving.queue.depth"):
        self.max_depth = max_depth
        self.max_batch_delay_ms = max_batch_delay_ms
        self.aging_s = aging_s
        # per-tier depth gauge name: a prefill-role engine publishes
        # ``serving.queue.depth.prefill``, a decode/unified one
        # ``serving.queue.depth`` (or ``.decode``) — the autoscaler can
        # then tell prefill pressure from decode pressure, and the
        # expiry sweep decrements the RIGHT tier because the sweep and
        # the gauge live on the same object
        self.depth_gauge = depth_gauge
        self._cv = threading.Condition()
        # index = priority tier: [INTERACTIVE, BACKGROUND]
        self._tiers: list[deque[PendingResult]] = [deque(), deque()]
        self._woken = False              # guarded-by: self._cv

    # -- locked helpers (caller holds self._cv) -------------------------
    def _total_locked(self) -> int:
        return len(self._tiers[INTERACTIVE]) + len(self._tiers[BACKGROUND])

    def _expire_locked(self, now: float) -> None:
        """Fail + remove every expired request in EITHER tier and
        republish the depth gauge — deadline expiry decrements queue
        depth at expiry, not at the next pop that reaches it."""
        swept = False
        for tier in self._tiers:
            live = [p for p in tier
                    if p.request.deadline_s is None
                    or now <= p.request.deadline_s]
            if len(live) == len(tier):
                continue
            for p in tier:
                dl = p.request.deadline_s
                if dl is not None and now > dl:
                    if p._fail(DeadlineExceeded(
                            f"request {p.request.id} expired after "
                            f"{now - p.request.submitted_s:.3f}s in queue")):
                        METRICS.increment("serving.deadline_dropped")
                        TENANTS.account("deadline_dropped",
                                        getattr(p.request, "tenant", ""))
            tier.clear()
            tier.extend(live)
            swept = True
        if swept:
            METRICS.gauge(self.depth_gauge, self._total_locked())

    def _pop_locked(self, now: float) -> PendingResult:
        """Next request in service order: an AGED background head beats
        everything (anti-starvation), then interactive, then background."""
        bg = self._tiers[BACKGROUND]
        if bg and now - bg[0].request.submitted_s >= self.aging_s:
            return bg.popleft()
        inter = self._tiers[INTERACTIVE]
        if inter:
            return inter.popleft()
        return bg.popleft()

    def submit(self, request) -> PendingResult:
        """Enqueue or reject — never blocks the submitter."""
        FAULTS.maybe_fire("serving.request")
        with self._cv:
            now = time.monotonic()
            # sweep first: during a burst, expired requests must free
            # their capacity for live ones instead of forcing a 429
            self._expire_locked(now)
            if self._total_locked() >= self.max_depth:
                METRICS.increment("serving.rejected")
                # ScoreRequest carries no tenant field; getattr keeps the
                # score path free of the attribute
                TENANTS.account("rejected", getattr(request, "tenant", ""))
                FLIGHTREC.note_429()
                raise QueueFull(
                    f"request queue full ({self.max_depth} deep) — retry "
                    "with backoff")
            request.submitted_s = now
            request.submitted_perf = time.perf_counter()
            pending = PendingResult(request)
            tier = BACKGROUND if getattr(request, "priority", 0) > 0 \
                else INTERACTIVE
            self._tiers[tier].append(pending)
            METRICS.gauge(self.depth_gauge, self._total_locked())
            self._cv.notify()
        return pending

    def take(self, max_n: int, block_s: float = 0.0) -> list[PendingResult]:
        """Up to ``max_n`` admissible requests.

        ``block_s > 0`` is the IDLE path: wait up to ``block_s`` for a
        first arrival (a condition-variable wakeup — ``submit`` and
        ``wake`` notify, so idle admission latency is the notify hop, not
        a polling interval; the timeout stays as a liveness fallback),
        then hold it up to ``max_batch_delay_ms`` for companions
        (coalescing).  ``block_s == 0`` is the busy path — return
        whatever is queued right now, the decode loop must not stall.
        Requests whose deadline already passed are completed
        exceptionally here and never returned.
        """
        if max_n <= 0:
            return []
        out: list[PendingResult] = []
        with self._cv:
            if not self._total_locked() and block_s > 0:
                # loop: condition waits wake spuriously and on unrelated
                # notifies — re-check the predicate until the deadline;
                # an explicit wake() (engine shutdown, slot freed) breaks
                # out immediately instead of riding out the timeout
                end = time.monotonic() + block_s
                while not self._total_locked() and not self._woken:
                    left = end - time.monotonic()
                    if left <= 0 or not self._cv.wait(left):
                        break
            self._woken = False
            if self._total_locked() and block_s > 0 \
                    and self._total_locked() < max_n \
                    and self.max_batch_delay_ms > 0:
                end = time.monotonic() + self.max_batch_delay_ms / 1000.0
                while self._total_locked() < max_n:
                    left = end - time.monotonic()
                    if left <= 0 or not self._cv.wait(left):
                        break
            now = time.monotonic()
            self._expire_locked(now)
            while self._total_locked() and len(out) < max_n:
                p = self._pop_locked(now)
                METRICS.observe_time("serving.queue_wait",
                                     now - p.request.submitted_s)
                TENANTS.account("queue_wait_s",
                                getattr(p.request, "tenant", ""),
                                now - p.request.submitted_s)
                out.append(p)
            METRICS.gauge(self.depth_gauge, self._total_locked())
        return out

    def claim(self, p: PendingResult) -> bool:
        """Atomic expiry-vs-admission arbiter (engine side).

        ``take()`` checks deadlines at pop time, but the engine occupies
        the decode slot later — a deadline expiring in that window used
        to admit an already-dead request (check-then-act).  The engine
        now calls ``claim`` at the moment it takes the slot: under the
        queue lock the request either expires here (completes with
        :class:`DeadlineExceeded`, never decodes) or is admitted — after
        a True claim the deadline no longer applies to admission.

        Claim time is ALSO the preemption point: a background request
        whose slot an interactive arrival now wants is pushed back to
        the head of its tier (still pending, re-taken later) and the
        claim returns False — the same "False means skip, not fail"
        contract the engine already honours for expiry races.  An aged
        background request is exempt, so preemption cannot starve.
        """
        with self._cv:
            if p.done():
                return False         # already failed (expiry/shutdown)
            dl = p.request.deadline_s
            now = time.monotonic()
            if dl is not None and now > dl:
                if p._fail(DeadlineExceeded(
                        f"request {p.request.id} expired after "
                        f"{now - p.request.submitted_s:.3f}s before "
                        f"admission")):
                    METRICS.increment("serving.deadline_dropped")
                    TENANTS.account("deadline_dropped",
                                    getattr(p.request, "tenant", ""))
                return False
            if (getattr(p.request, "priority", 0) > 0
                    and self._tiers[INTERACTIVE]
                    and now - p.request.submitted_s < self.aging_s):
                self._tiers[BACKGROUND].appendleft(p)
                METRICS.increment("serving.preempted")
                METRICS.gauge(self.depth_gauge, self._total_locked())
                self._cv.notify()
                return False
            return True

    def wake(self) -> None:
        """Kick any idle ``take`` out of its wait immediately — called on
        engine shutdown (so the serve loop observes the stop flag without
        riding out ``idle_wait_s``) and when a decode slot frees while
        the loop is parked (so a queued request is admitted on the notify
        hop instead of the next poll)."""
        with self._cv:
            self._woken = True
            self._cv.notify_all()

    def depth(self) -> int:
        """Live queued requests — expired ones are swept (and their
        depth-gauge contribution dropped) before counting, so the
        autoscaler's primary signal never includes dead work."""
        with self._cv:
            self._expire_locked(time.monotonic())
            return self._total_locked()

    def drain(self) -> list[PendingResult]:
        """Remove and return everything queued (engine shutdown path)."""
        with self._cv:
            out = list(self._tiers[INTERACTIVE]) \
                + list(self._tiers[BACKGROUND])
            for tier in self._tiers:
                tier.clear()
            METRICS.gauge(self.depth_gauge, 0)
        return out

    def unclaim(self, p: PendingResult) -> None:
        """Push a previously taken request back to the HEAD of its tier
        (disagg prefill-worker death: the scheduler requeues the request
        rather than failing it — head position preserves arrival order
        so a chaos-killed worker costs latency, never fairness)."""
        with self._cv:
            if p.done():
                return               # already failed (expiry/shutdown)
            tier = BACKGROUND if getattr(p.request, "priority", 0) > 0 \
                else INTERACTIVE
            self._tiers[tier].appendleft(p)
            METRICS.gauge(self.depth_gauge, self._total_locked())
            self._cv.notify()
