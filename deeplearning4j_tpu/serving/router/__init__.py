"""Multi-replica serving tier: a prefix-affinity consistent-hash router
in front of N inference engines (DESIGN.md §19).

:class:`PrefixRouter` hashes each request by its content-addressed
prefix chain (the same chained page hash the KV :class:`~..paging.PagePool`
uses) onto a virtual-node :class:`~.ring.HashRing` of replicas, so
repeated system prompts land on the replica that already holds their KV
pages.  :class:`~.replicas.ReplicaPool` supplies breaker-style health
(quarantine on consecutive failures, re-admission on probe recovery),
:class:`RouterServer` exposes the single-replica ``ModelServer`` HTTP
surface unchanged, and replicas are either in-process engines
(:class:`~.replicas.EngineReplica`) or spawned ``ModelServer`` processes
(:class:`~.replicas.ProcessReplica`).
"""

from .replicas import (AllReplicasUnavailable, EngineReplica, ProcessReplica,
                       Replica, ReplicaPool, ReplicaUnavailable)
from .ring import HashRing
from .router import PrefixRouter, RouterConfig
from .server import RouterServer

__all__ = [
    "AllReplicasUnavailable",
    "EngineReplica",
    "HashRing",
    "PrefixRouter",
    "ProcessReplica",
    "Replica",
    "ReplicaPool",
    "ReplicaUnavailable",
    "RouterConfig",
    "RouterServer",
]
