"""Kernel candidate registry + the evidence-gated auto-pick.

One registration API for every accelerated op in the tree (the flash
attention kernel predates this package and registers through the same
surface — no parallel mechanisms).  A candidate bundles the kernel entry
point, its pure-jnp reference, the block configs the TUNE battery should
sweep, and the documented correctness tolerances the adoption gate
enforces.

``autopick`` is the decision procedure bench.py's pickers share: a
candidate replaces the incumbent only when

1. a TUNE battery row proves it *correct* (its ``check`` dict passes the
   candidate's tolerances — ``max_err``-style upper bounds and/or
   ``min``-keyed lower bounds such as int8's top-1 agreement), and
2. its best measured metric beats the incumbent's best by the >2% margin
   (one noisy row must not flip a production config), where a 0.0 row is
   EVIDENCE of a broken config, not missing data, and no incumbent
   evidence means no adoption (never adopt by void).

Losers stay registered but unpicked; every dropped candidate lands in
``Pick.dropped`` with the reason, so the bench artifact's pick table has
no silent caps.
"""

from __future__ import annotations

import dataclasses
import importlib
from typing import Any, Callable, Iterable, Mapping

#: kernel modules pulled in lazily so importing the registry never drags
#: jax.experimental.pallas in (and a broken/missing pallas degrades to
#: "candidate absent", recorded in _IMPORT_ERRORS, instead of an
#: ImportError at package import)
_KERNEL_MODULES = (
    "deeplearning4j_tpu.ops.pallas.attention",
    "deeplearning4j_tpu.ops.pallas.layernorm",
    "deeplearning4j_tpu.ops.pallas.xent",
    "deeplearning4j_tpu.ops.pallas.matmul_int8",
    "deeplearning4j_tpu.ops.pallas.paged_attention",
    "deeplearning4j_tpu.ops.flash_attention",
)


@dataclasses.dataclass(frozen=True)
class KernelCandidate:
    """One selectable implementation of a kernel kind."""

    kind: str                     # "attention" | "layernorm_residual" | ...
    name: str                     # registry key within the kind
    fn: Callable                  # kernel entry point (jnp-compatible API)
    reference: Callable | None = None   # pure-jnp ground truth
    blocks: tuple = ()            # block configs the TUNE battery sweeps
    tolerances: Mapping[str, Any] = dataclasses.field(default_factory=dict)
    source: str = "pallas"        # "pallas" kernel or "xla" incumbent


@dataclasses.dataclass
class Pick:
    """One auto-pick decision, artifact-ready via :meth:`as_dict`."""

    kind: str
    choice: str
    reason: str
    dropped: list            # [{"candidate": name, "reason": why}, ...]
    considered: int          # TUNE rows consulted for this kind

    def as_dict(self) -> dict:
        return {"choice": self.choice, "reason": self.reason,
                "dropped": self.dropped, "rows_considered": self.considered}


_REGISTRY: dict[tuple[str, str], KernelCandidate] = {}
_IMPORT_ERRORS: dict[str, str] = {}
_LOADED = False


def register(candidate: KernelCandidate) -> KernelCandidate:
    """Register a candidate; re-registration with identical identity is a
    no-op (kernels register at module import, which can run twice under
    importlib reload), a *different* candidate under a taken key is a
    programming error."""
    key = (candidate.kind, candidate.name)
    prev = _REGISTRY.get(key)
    if prev is not None and prev.fn is not candidate.fn:
        raise ValueError(f"kernel candidate {key} already registered")
    _REGISTRY[key] = candidate
    return candidate


def _ensure_loaded() -> None:
    global _LOADED
    if _LOADED:
        return
    _LOADED = True
    for mod in _KERNEL_MODULES:
        try:
            importlib.import_module(mod)
        except Exception as e:  # degraded wheel: candidate absent, recorded
            _IMPORT_ERRORS[mod] = repr(e)[:200]


def import_errors() -> dict:
    """Kernel modules that failed to import (empty on a healthy wheel)."""
    _ensure_loaded()
    return dict(_IMPORT_ERRORS)


def kinds() -> list[str]:
    _ensure_loaded()
    return sorted({k for k, _ in _REGISTRY})


def candidates(kind: str) -> list[KernelCandidate]:
    _ensure_loaded()
    return [c for (k, _), c in sorted(_REGISTRY.items()) if k == kind]


def get(kind: str, name: str) -> KernelCandidate:
    _ensure_loaded()
    try:
        return _REGISTRY[(kind, name)]
    except KeyError:
        avail = [c.name for c in candidates(kind)]
        raise KeyError(
            f"no kernel candidate {name!r} of kind {kind!r} "
            f"(registered: {avail})") from None


# --------------------------------------------------------------- adoption gate

def check_passes(cand: KernelCandidate, check: Mapping) -> tuple[bool, str]:
    """Apply ``cand.tolerances`` to one TUNE ``check`` row.

    Plain keys in ``tolerances`` (e.g. ``max_err``) upper-bound every
    numeric value in the check row; the nested ``min`` mapping
    lower-bounds named keys (e.g. ``{"min": {"top1_agree": 0.999}}``).
    """
    if not isinstance(check, Mapping) or not check:
        return False, "empty correctness row"
    mins = cand.tolerances.get("min", {})
    max_err = cand.tolerances.get("max_err")
    for key, val in check.items():
        if not isinstance(val, (int, float)) or isinstance(val, bool):
            return False, f"non-numeric check value {key}={val!r}"
        if key in mins:
            if val < mins[key]:
                return False, f"{key}={val} below required {mins[key]}"
        elif max_err is not None and val >= max_err:
            return False, f"{key}={val} exceeds tolerance {max_err}"
    return True, "check passed"


def _best_metric(rows: Iterable[Mapping], name: str, metric: str):
    vals = [r[metric] for r in rows
            if r.get("candidate") == name
            and isinstance(r.get(metric), (int, float))
            and not isinstance(r.get(metric), bool)]
    return max(vals) if vals else None


def autopick(kind: str, rows: Iterable[Mapping], *, incumbent: str,
             metric: str = "tokens_per_sec", margin: float = 1.02) -> Pick:
    """Pick the production implementation for ``kind`` from TUNE rows.

    ``rows`` are battery JSONL dicts; this consumes the generic schema
    ``{"kernel": kind, "candidate": name, <metric>: float}`` for
    measurements and ``{"kernel": kind, "candidate": name, "check":
    {...}}`` for correctness evidence (bench.py adapts its legacy
    per-kind row shapes into this).
    """
    _ensure_loaded()
    rows = [r for r in rows if isinstance(r, Mapping)
            and r.get("kernel") == kind]
    inc_best = _best_metric(rows, incumbent, metric)
    dropped: list[dict] = []
    eligible: list[tuple[float, KernelCandidate]] = []
    for cand in candidates(kind):
        if cand.name == incumbent:
            continue
        best = _best_metric(rows, cand.name, metric)
        if best is None:
            dropped.append({"candidate": cand.name,
                            "reason": f"no TUNE {metric} rows"})
            continue
        checks = [r["check"] for r in rows
                  if r.get("candidate") == cand.name
                  and isinstance(r.get("check"), Mapping)]
        if not checks:
            dropped.append({"candidate": cand.name,
                            "reason": "no correctness evidence"})
            continue
        verdicts = [check_passes(cand, c) for c in checks]
        if not any(ok for ok, _ in verdicts):
            dropped.append({"candidate": cand.name,
                            "reason": f"correctness gate: {verdicts[0][1]}"})
            continue
        if inc_best is None:
            dropped.append({"candidate": cand.name,
                            "reason": f"no incumbent ({incumbent}) evidence "
                                      "— never adopt by void"})
            continue
        if best <= inc_best * margin:
            dropped.append({"candidate": cand.name,
                            "reason": f"{metric} {best:.4g} within {margin:g}x"
                                      f" of {incumbent} {inc_best:.4g} "
                                      "(no >2% margin)"})
            continue
        eligible.append((best, cand))

    if eligible:
        eligible.sort(key=lambda bc: bc[0], reverse=True)
        best, winner = eligible[0]
        for lost, cand in eligible[1:]:
            dropped.append({"candidate": cand.name,
                            "reason": f"passed the gate but lost to "
                                      f"{winner.name} ({lost:.4g} vs "
                                      f"{best:.4g} {metric})"})
        pick = Pick(kind, winner.name,
                    f"TUNE: {winner.name} {best:.4g} > {incumbent} "
                    f"{inc_best:.4g} {metric} (>2% margin), check passed",
                    dropped, len(rows))
    else:
        pick = Pick(kind, incumbent,
                    f"default ({incumbent}: no TUNE evidence that a "
                    "candidate wins by >2%)", dropped, len(rows))

    try:  # observability is core, but the pick must survive without it
        from ...observability.kernels import publish_autopick
        publish_autopick(pick)
    except Exception:
        pass
    return pick
