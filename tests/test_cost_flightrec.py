"""PR 10: analytic cost/MFU accounting, the failure flight recorder, the
device-memory degradation path, and the trace_report / metrics_dump tools.

The serving- and supervisor-side integration of these pieces is covered in
test_serving.py / test_resilience.py; this file owns the units.
"""

import json
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import observability as obs
from deeplearning4j_tpu.observability import (
    COSTS,
    FLIGHTREC,
    METRICS,
    TRACER,
    CostInfo,
    trace,
)
from deeplearning4j_tpu.observability.cost import CostModel
from deeplearning4j_tpu.observability.flightrec import FlightRecorder


# --------------------------------------------------------------------------- cost model

@jax.jit
def _toy_step(x, w):
    return jnp.sum(x @ w)


def _toy_args(n=64):
    return (jnp.ones((n, n), jnp.float32), jnp.ones((n, n), jnp.float32))


def test_capture_pulls_xla_flops_on_cpu():
    model = CostModel()
    info = model.capture("toy.step", _toy_step, *_toy_args())
    assert info is not None and info.source == "xla"
    assert info.flops > 0 and math.isfinite(info.flops)
    assert model.get("toy.step") is info


def test_capture_caches_per_signature():
    model = CostModel()
    first = model.capture("toy.step", _toy_step, *_toy_args())
    calls = []
    real_lower = _toy_step.lower

    class Spy:
        def lower(self, *a):
            calls.append(a)
            return real_lower(*a)

    again = model.capture("toy.step", Spy(), *_toy_args())
    assert again is first            # signature hit: lower never invoked
    assert calls == []
    other = model.capture("toy.step", Spy(), *_toy_args(32))
    assert calls                     # new shapes -> new compile
    assert other is not first


def test_capture_falls_back_to_analytic_flops():
    model = CostModel()

    class NoCost:
        def lower(self, *a):
            raise RuntimeError("backend returned no cost_analysis")

    info = model.capture("fallback", NoCost(), *_toy_args(),
                         analytic_flops=123.0)
    assert info == CostInfo(123.0, 0.0, "analytic")
    assert model.capture("nothing", NoCost(), *_toy_args()) is None


def test_capture_is_noop_while_disabled():
    model = CostModel()
    obs.disable()
    try:
        assert model.capture("toy.step", _toy_step, *_toy_args()) is None
    finally:
        obs.enable()
    assert model.get("toy.step") is None


def test_publish_utilization_gauges_finite_mfu():
    model = CostModel()
    info = model.capture("toy.step", _toy_step, *_toy_args())
    mfu = model.publish_utilization(info, 1e-3, "toy.mfu", "toy.mbu")
    gauges = METRICS.snapshot()["gauges"]
    assert mfu is not None and math.isfinite(mfu) and mfu > 0
    assert gauges["toy.mfu"] == pytest.approx(mfu)
    assert "toy.mbu" in gauges and math.isfinite(gauges["toy.mbu"])
    # None cost / zero time publish nothing rather than NaN
    assert model.publish_utilization(None, 1e-3, "x.mfu") is None
    assert model.publish_utilization(info, 0.0, "x.mfu") is None
    assert "x.mfu" not in METRICS.snapshot()["gauges"]


def test_trainer_publishes_train_mfu_on_cpu():
    """Acceptance: a CPU fit publishes finite train.mfu/train.mbu from
    cost_analysis of the actual compiled step."""
    from deeplearning4j_tpu.optimize import transforms as T
    from deeplearning4j_tpu.parallel.trainer import DataParallelTrainer

    def loss_fn(p, x, y, key=None):
        return jnp.mean((x @ p["w"] - y) ** 2)

    tr = DataParallelTrainer(loss_fn, T.sgd_lr(0.1))
    state = tr.init_state({"w": np.zeros((4, 2), np.float32)})
    xs = np.ones((16, 4), np.float32)
    ys = np.ones((16, 2), np.float32)
    for _ in range(3):
        state, _ = tr.step(state, xs, ys)
    tr._resolve_pending()
    gauges = METRICS.snapshot()["gauges"]
    assert math.isfinite(gauges["train.mfu"]) and gauges["train.mfu"] > 0
    assert math.isfinite(gauges["train.mbu"]) and gauges["train.mbu"] > 0
    assert tr._step_cost is not None and tr._step_cost.flops > 0


# --------------------------------------------------------------------------- device memory degradation

def test_sample_device_memory_degrades_on_cpu():
    """Satellite 6: the CPU backend has no memory_stats — sampling stays
    a no-op gauge (supported=0) instead of raising or publishing junk."""
    from deeplearning4j_tpu.observability.device import sample_device_memory

    reported = sample_device_memory()
    gauges = METRICS.snapshot()["gauges"]
    assert reported == 0
    assert gauges["device.memory_stats_supported"] == 0.0
    assert not any(k.startswith("device.") and k.endswith("bytes_in_use")
                   for k in gauges)


# --------------------------------------------------------------------------- flight recorder

def test_flightrec_rings_capture_spans_metrics_and_faults(tmp_path):
    rec = FlightRecorder(dump_dir=tmp_path)
    rec.record_span({"name": "train_step", "ts": 1.0, "dur": 2.0,
                     "args": {"trace_id": "t1", "step": 7}})
    rec.record_metric("counter", "train.steps", 1.0)
    rec.record_metric("counter", "faults.injected.train.step", 1.0)
    assert rec.spans[-1]["step"] == 7
    assert ("counter", "train.steps", 1.0) in rec.metric_events
    assert rec.faults[-1]["site"] == "train.step"
    path = rec.dump("unit_test", extra={"k": "v"})
    bundle = json.loads(path.read_text())
    assert bundle["trigger"] == "unit_test"
    assert bundle["extra"] == {"k": "v"}
    assert bundle["spans"][-1]["name"] == "train_step"
    assert bundle["faults"][-1]["site"] == "train.step"
    assert "metrics" in bundle       # full registry snapshot rides along


def test_flightrec_global_wiring_sees_spans_and_chaos_fires():
    """The singleton listens passively: spans and faults.injected.*
    counters land in its rings with no caller-side wiring."""
    FLIGHTREC.clear()
    with trace.span("wired_span"):
        pass
    METRICS.increment("faults.injected.some.site")
    assert any(s["name"] == "wired_span" for s in FLIGHTREC.spans)
    assert any(f["site"] == "some.site" for f in FLIGHTREC.faults)


def test_flightrec_429_burst_dumps_once(tmp_path):
    rec = FlightRecorder(dump_dir=tmp_path)
    rec.burst_n = 5
    paths = [rec.note_429() for _ in range(12)]
    dumps = [p for p in paths if p is not None]
    assert len(dumps) == 1           # burst fired once, cooldown holds
    bundle = json.loads(dumps[0].read_text())
    assert bundle["trigger"] == "serving_429_burst"
    assert bundle["extra"]["rejections_in_window"] == 5


def test_flightrec_disabled_is_allocation_free(tmp_path):
    rec = FlightRecorder(dump_dir=tmp_path)
    obs.disable()
    try:
        rec.record_span({"name": "x", "ts": 0, "dur": 0, "args": {}})
        rec.record_metric("counter", "faults.injected.x", 1.0)
        assert rec.note_429() is None
        assert rec.dump("nope") is None
    finally:
        obs.enable()
    assert not rec.spans and not rec.metric_events and not rec.faults
    assert not list(tmp_path.iterdir())


# --------------------------------------------------------------------------- tools

def test_trace_report_merges_and_breaks_down(tmp_path):
    from tools.trace_report import load_events, merge, request_breakdowns

    def ev(name, ts, dur, trace_id, **args):
        return {"name": name, "ph": "X", "ts": ts, "dur": dur, "pid": 1,
                "tid": 1, "args": dict(args, trace_id=trace_id)}

    chrome = {"traceEvents": [
        ev("serving.request", 0.0, 1000.0, "t1", tokens=5),
        ev("serving.queue_wait", 0.0, 100.0, "t1"),
        ev("serving.prefill", 100.0, 50.0, "t1"),
    ], "metadata": {"dropped": 2}}
    (tmp_path / "a.json").write_text(json.dumps(chrome))
    with open(tmp_path / "b.jsonl", "w") as f:
        f.write(json.dumps(ev("serving.decode.segment", 150.0, 700.0, "t1")) + "\n")
        f.write(json.dumps(ev("serving.emit", 900.0, 100.0, "t1")) + "\n")
        f.write(json.dumps(ev("serving.prefill", 0.0, 10.0, "t_inflight")) + "\n")
        f.write("{torn line")         # crashed streamer tail is tolerated

    merged = merge([str(tmp_path / "a.json"), str(tmp_path / "b.jsonl")])
    assert len(merged["traceEvents"]) == 6
    assert merged["metadata"]["dropped"] == 2
    ts = [e["ts"] for e in merged["traceEvents"]]
    assert ts == sorted(ts)

    rows = request_breakdowns(merged["traceEvents"])
    (row,) = rows                    # t_inflight has no root -> skipped
    assert row["trace_id"] == "t1"
    assert row["queue_wait_ms"] == pytest.approx(0.1)
    assert row["prefill_ms"] == pytest.approx(0.05)
    assert row["decode_ms"] == pytest.approx(0.7)
    assert row["emit_ms"] == pytest.approx(0.1)
    assert row["ttft_ms"] == pytest.approx(0.15)
    assert row["total_ms"] == pytest.approx(1.0)
    assert row["tokens"] == 5

    events, dropped = load_events(tmp_path / "b.jsonl")
    assert len(events) == 3 and dropped == 0


def test_metrics_dump_renders_serving_and_utilization_tables():
    from tools.metrics_dump import render_serving, render_utilization

    snap = {
        "counters": {},
        "gauges": {
            "serving.kv_pages_in_use": 12.0,
            "serving.prefix_hit_rate": 0.75,
            "serving.kv_bytes_per_slot": 4096.0,
            "train.mfu": 0.41,
            "serving.decode_mfu": 0.22,
            "serving.decode_mbu": 0.6,
        },
        "timers": {
            "serving.spec_accept_len": {"count": 9, "mean_s": 2.5,
                                        "p50_s": 2.0, "p95_s": 4.0,
                                        "p99_s": 4.0, "total_s": 22.5},
        },
    }
    serving = render_serving(snap)
    assert "kv_pages_in_use" in serving and "12" in serving
    assert "75.0%" in serving
    assert "4.00KiB" in serving
    assert "2.50 tok" in serving
    util = render_utilization(snap)
    assert "train.mfu" in util and "41.00%" in util
    assert "serving.decode_mfu" in util and "22.00%" in util
    # absent gauges -> absent tables, not crashes
    empty = {"counters": {}, "gauges": {}, "timers": {}}
    assert render_serving(empty) is None
    assert render_utilization(empty) is None


def test_metrics_dump_renders_kv_capacity_table():
    """The users-per-chip table (DESIGN.md §20): derived rows — pool
    bytes, bytes per slot, slots per pool — from the kv gauges; absent
    gauges mean no table, not a crash."""
    from tools.metrics_dump import render_kv_capacity

    snap = {
        "counters": {},
        "gauges": {
            "serving.kv_quant_bits": 8.0,
            "serving.kv_pages_total": 64.0,
            "serving.kv_page_bytes": 576.0,
            "serving.kv_pages_in_use": 16.0,
            "serving.kv_bytes_per_slot": 4032.0,
        },
        "timers": {},
    }
    table = render_kv_capacity(snap)
    assert "kv_storage_bits" in table and "8" in table
    assert "pool_pages" in table and "64" in table
    assert "slots_per_pool" in table
    # pool_bytes = page_bytes * pages_total = 36864 -> 36.00KiB
    assert "36.00KiB" in table
    # slots = pool_bytes // bytes_per_slot = 9
    assert "9" in table
    empty = {"counters": {}, "gauges": {}, "timers": {}}
    assert render_kv_capacity(empty) is None
