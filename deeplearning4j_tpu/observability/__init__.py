"""Observability: structured tracing + metrics + status/metrics HTTP.

The production observability layer (grown from the seed
``parallel/observe.py``; that module remains as a compat shim):

- ``trace`` (module alias) / ``span`` — nestable spans with trace identity
  (``trace_id``/``span_id``/``parent_id``, W3C ``traceparent`` propagation
  via ``trace.bind``/``trace.current_traceparent``), contextvar
  propagation, Chrome-trace (Perfetto) + JSONL export (``tracing``)
- ``METRICS`` / ``MetricsRegistry`` — counters, gauges, timing histograms
  with p50/p95/p99, Prometheus text exposition (``metrics``)
- ``COSTS`` / ``CostModel`` — XLA ``cost_analysis()`` FLOPs/bytes per
  compiled signature; live ``*.mfu`` / ``*.mbu`` gauges (``cost``)
- ``FLIGHTREC`` — bounded rings of recent spans/metric deltas/chaos fires,
  dumped to a JSON bundle on failure triggers (``flightrec``)
- ``TimeSeriesStore`` — background sampler turning the registry into
  bounded per-series rings + JSONL history (``timeseries``)
- ``GoodputTracker`` — wall-clock state accounting for supervised runs:
  productive/checkpoint/restore/rollback/stall/drain (``goodput``)
- ``SLObjective``/``SLOEvaluator`` — rolling-window objectives with
  multi-window error-budget burn rates; breaches dump flightrec bundles
  and publish ``slo.burn_rate.*`` (``slo``)
- ``FleetScraper``/``FederatedRegistry`` — metric federation across a
  replica pool; ``TENANTS`` bounded tenant labels; ``ForecastEvaluator``
  time-to-breach extrapolation (``fleet``)
- ``StatusServer`` — ``/healthz`` ``/metrics`` ``/metrics.prom`` ``/status``
- ``sample_device_memory`` — per-device HBM gauges (no-op gauge on
  backends without memory stats)
- ``enabled``/``enable``/``disable`` — process-global flag;
  zero-per-step-allocation when off (see ``core``)
"""

from . import tracing as trace
from .core import NOOP_SPAN, disable, enable, enabled
from .cost import COSTS, CostInfo, CostModel
from .device import sample_device_memory, sample_state_bytes
from .fleet import (
    TENANTS,
    FederatedRegistry,
    FleetScraper,
    ForecastEvaluator,
    TenantLabels,
    parse_prometheus,
)
from .flightrec import FLIGHTREC, FlightRecorder
from .metrics import (
    DEFAULT_TIME_BUCKETS,
    METRICS,
    Histogram,
    MetricsRegistry,
    StepTimer,
)
from .goodput import GoodputTracker
from .server import StatusServer
from .slo import SLObjective, SLOEvaluator
from .slo import default_serving_objectives, default_training_objectives
from .timeseries import TimeSeriesStore
from .tracing import TRACER, Tracer, profiler_trace, span

__all__ = [
    "COSTS", "CostInfo", "CostModel", "DEFAULT_TIME_BUCKETS", "FLIGHTREC",
    "FederatedRegistry", "FleetScraper", "FlightRecorder",
    "ForecastEvaluator", "GoodputTracker", "Histogram", "METRICS",
    "MetricsRegistry", "NOOP_SPAN", "SLOEvaluator", "SLObjective",
    "StatusServer", "StepTimer", "TENANTS", "TRACER", "TenantLabels",
    "TimeSeriesStore", "Tracer",
    "default_serving_objectives", "default_training_objectives",
    "disable", "enable", "enabled", "parse_prometheus", "profiler_trace",
    "sample_device_memory", "sample_state_bytes", "span", "trace",
]
