"""Gradient-parity suite for the ops/pallas kernel tier.

Every kernel candidate must match its pure-jnp reference forward AND
backward, in Pallas interpret mode on CPU (the same code compiles to
Mosaic on TPU), at odd/near-prime shapes and in both f32 and bf16 — plus
unit coverage of the candidate registry and the evidence-gated auto-pick
that decides what production runs.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops.pallas import registry
from deeplearning4j_tpu.ops.pallas.attention import (fused_attention,
                                                     reference_attention)
from deeplearning4j_tpu.ops.pallas.layernorm import (
    fused_residual_layernorm, reference_residual_layernorm)
from deeplearning4j_tpu.ops.pallas.matmul_int8 import (
    dequantize, int8_matmul, quantize, quantize_params_for_decode,
    reference_int8_matmul, top1_agreement)
from deeplearning4j_tpu.ops.pallas.xent import (blocked_cross_entropy,
                                                reference_xent_sum)

F32_TOL = dict(atol=2e-5, rtol=3e-5)
# bf16 inputs: reference and kernel round differently mid-pipeline
BF16_TOL = dict(atol=3e-2, rtol=3e-2)


def _tol(dtype):
    return F32_TOL if dtype == jnp.float32 else BF16_TOL


def _close(a, b, dtype):
    np.testing.assert_allclose(np.asarray(a, np.float32),
                               np.asarray(b, np.float32), **_tol(dtype))


# ------------------------------------------------------------------ registry

def test_registry_kinds_and_candidates_complete():
    assert registry.import_errors() == {}
    assert registry.kinds() == ["attention", "int8_matmul",
                                "layernorm_residual", "paged_attention",
                                "paged_attention_int8", "xent"]
    assert [c.name for c in registry.candidates("attention")] == [
        "flash", "fused", "ring"]
    # every pallas candidate ships a reference and documented tolerances
    for kind in registry.kinds():
        for c in registry.candidates(kind):
            assert c.reference is not None, (kind, c.name)
            if c.source == "pallas":
                assert c.tolerances, (kind, c.name)
                assert c.blocks, (kind, c.name)


def test_registry_get_unknown_lists_registered():
    with pytest.raises(KeyError, match="flash"):
        registry.get("attention", "nope")


def test_registry_reregistration_same_fn_is_noop_different_fn_raises():
    cand = registry.get("attention", "fused")
    registry.register(cand)                       # idempotent
    clash = dataclasses.replace(cand, fn=lambda *a, **k: None)
    with pytest.raises(ValueError, match="already registered"):
        registry.register(clash)


# ------------------------------------------------------------------ autopick

def _rows(kind, cand, metric_vals, check=None, incumbent=None, inc_vals=()):
    rows = []
    if check is not None:
        rows.append({"kernel": kind, "candidate": cand, "check": check})
    rows += [{"kernel": kind, "candidate": cand, "tokens_per_sec": v}
             for v in metric_vals]
    rows += [{"kernel": kind, "candidate": incumbent, "tokens_per_sec": v}
             for v in inc_vals]
    return rows


def test_autopick_needs_margin_and_correctness():
    ok = {"max_err": 1e-4}
    win = registry.autopick("attention", _rows(
        "attention", "fused", [103.0], ok, "ring", [100.0]), incumbent="ring")
    assert win.choice == "fused" and "TUNE" in win.reason
    # 1% is inside jitter -> incumbent, with the loser's reason on record
    jit = registry.autopick("attention", _rows(
        "attention", "fused", [101.0], ok, "ring", [100.0]), incumbent="ring")
    assert jit.choice == "ring"
    assert any(d["candidate"] == "fused" and "margin" in d["reason"]
               for d in jit.dropped)
    # failed correctness gate -> speed win is irrelevant
    bad = registry.autopick("attention", _rows(
        "attention", "fused", [200.0], {"max_err": 0.2}, "ring", [100.0]),
        incumbent="ring")
    assert bad.choice == "ring"
    assert any("correctness" in d["reason"] for d in bad.dropped)


def test_autopick_zero_throughput_and_void_are_evidence():
    ok = {"max_err": 1e-4}
    # 0.0 tok/s is a broken config, not missing data
    zero = registry.autopick("attention", _rows(
        "attention", "fused", [0.0], ok, "ring", [100.0]), incumbent="ring")
    assert zero.choice == "ring"
    # no incumbent evidence at all -> never adopt by void
    void = registry.autopick("attention", _rows(
        "attention", "fused", [103.0], ok), incumbent="ring")
    assert void.choice == "ring"
    assert any("void" in d["reason"] for d in void.dropped)


def test_autopick_every_loser_lands_in_dropped():
    pick = registry.autopick("attention", [], incumbent="ring")
    assert pick.choice == "ring"
    assert {d["candidate"] for d in pick.dropped} == {"flash", "fused"}
    assert pick.as_dict()["rows_considered"] == 0


def test_autopick_int8_min_gate():
    # int8 adoption needs top-1 agreement ABOVE the floor, not just a
    # small max_err — the "min" tolerance direction
    rows = _rows("int8_matmul", "pallas_int8", [200.0],
                 {"max_err": 1e-4, "top1_agree": 0.9},   # disagreement!
                 "f32", [100.0])
    pick = registry.autopick("int8_matmul", rows, incumbent="f32")
    assert pick.choice == "f32"
    rows = _rows("int8_matmul", "pallas_int8", [200.0],
                 {"max_err": 1e-4, "top1_agree": 1.0}, "f32", [100.0])
    assert registry.autopick("int8_matmul", rows,
                             incumbent="f32").choice == "pallas_int8"


# ---------------------------------------------------------- fused attention

@pytest.mark.strict_dtypes
@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_fused_attention_forward_parity(causal, dtype):
    ks = jax.random.split(jax.random.key(0), 3)
    q, k, v = (jax.random.normal(kk, (2, 256, 3, 16), dtype) for kk in ks)
    got = fused_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    want = reference_attention(q, k, v, causal=causal)
    _close(got, want, dtype)


@pytest.mark.parametrize("causal", [False, True])
def test_fused_attention_gradient_parity(causal):
    ks = jax.random.split(jax.random.key(1), 3)
    q, k, v = (jax.random.normal(kk, (1, 128, 2, 8), jnp.float32)
               for kk in ks)

    def loss(fn):
        return lambda q, k, v: jnp.sum(jnp.sin(fn(q, k, v, causal=causal)))

    g1 = jax.grad(loss(fused_attention), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss(reference_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        _close(a, b, jnp.float32)


def test_fused_attention_block_sweep_and_frontier():
    """Asymmetric block configs exercise the traced frontier bound: the
    kernel must stay exact when block_q != block_k."""
    ks = jax.random.split(jax.random.key(2), 3)
    q, k, v = (jax.random.normal(kk, (1, 512, 1, 8), jnp.float32)
               for kk in ks)
    want = reference_attention(q, k, v, causal=True)
    for bq, bk in ((128, 128), (256, 128), (128, 256), (512, 512)):
        got = fused_attention(q, k, v, causal=True, block_q=bq, block_k=bk)
        _close(got, want, jnp.float32)


# ------------------------------------------------------- fused ln + residual

@pytest.mark.strict_dtypes
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_fused_layernorm_forward_parity_odd_rows(dtype):
    # 101 rows: prime, forces the internal pad-and-slice path
    ks = jax.random.split(jax.random.key(3), 4)
    x = jax.random.normal(ks[0], (101, 48), dtype)
    r = jax.random.normal(ks[1], (101, 48), dtype)
    scale = jax.random.normal(ks[2], (48,)) + 1.0
    bias = jax.random.normal(ks[3], (48,))
    y1, h1 = fused_residual_layernorm(x, r, scale, bias, block_rows=32)
    y2, h2 = reference_residual_layernorm(x, r, scale, bias)
    _close(y1, y2, dtype)
    _close(h1, h2, dtype)


def test_fused_layernorm_gradient_parity_with_mask():
    ks = jax.random.split(jax.random.key(4), 5)
    x = jax.random.normal(ks[0], (67, 32), jnp.float32)
    r = jax.random.normal(ks[1], (67, 32), jnp.float32)
    scale = jax.random.normal(ks[2], (32,)) + 1.0
    bias = jax.random.normal(ks[3], (32,))
    mask = (jax.random.uniform(ks[4], (67, 1)) > 0.3).astype(jnp.float32)

    def loss(fn):
        def l(x, r, scale, bias):
            y, h = fn(x, r, scale, bias, mask=mask)
            return jnp.sum(jnp.sin(h)) + 0.1 * jnp.sum(y)
        return l

    g1 = jax.grad(loss(fused_residual_layernorm), argnums=(0, 1, 2, 3))(
        x, r, scale, bias)
    g2 = jax.grad(loss(reference_residual_layernorm), argnums=(0, 1, 2, 3))(
        x, r, scale, bias)
    for a, b in zip(g1, g2):
        _close(a, b, jnp.float32)


def test_fused_layernorm_batched_shape_roundtrip():
    x = jax.random.normal(jax.random.key(5), (2, 37, 16), jnp.float32)
    r = jnp.zeros_like(x)
    y, h = fused_residual_layernorm(x, r, jnp.ones((16,)), jnp.zeros((16,)))
    assert y.shape == h.shape == x.shape
    _close(y, x, jnp.float32)


# ------------------------------------------------------------- blocked xent

@pytest.mark.strict_dtypes
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_blocked_xent_forward_parity_near_prime(dtype):
    # N=101 (prime) tokens, V=77 (odd, not a multiple of any block):
    # both pad/mask paths fire
    ks = jax.random.split(jax.random.key(6), 4)
    h = jax.random.normal(ks[0], (101, 24), dtype)
    head = (jax.random.normal(ks[1], (24, 77)) * 0.2).astype(dtype)
    t = jax.random.randint(ks[2], (101,), 0, 77)
    w = jax.random.uniform(ks[3], (101,))
    got = blocked_cross_entropy(h, head, t, w, block_t=32, block_v=16)
    want = reference_xent_sum(h, head, t, w)
    np.testing.assert_allclose(float(got), float(want),
                               rtol=1e-2 if dtype == jnp.bfloat16 else 1e-5)


def test_blocked_xent_gradient_parity():
    ks = jax.random.split(jax.random.key(7), 4)
    h = jax.random.normal(ks[0], (101, 16), jnp.float32)
    head = jax.random.normal(ks[1], (16, 53)) * 0.3
    t = jax.random.randint(ks[2], (101,), 0, 53)
    w = jax.random.uniform(ks[3], (101,))
    g1 = jax.grad(lambda h, hd, w: blocked_cross_entropy(
        h, hd, t, w, block_t=32, block_v=16), argnums=(0, 1, 2))(h, head, w)
    g2 = jax.grad(lambda h, hd, w: reference_xent_sum(h, hd, t, w),
                  argnums=(0, 1, 2))(h, head, w)
    for a, b in zip(g1, g2):
        _close(a, b, jnp.float32)


def test_blocked_xent_under_jit_and_weightless():
    h = jax.random.normal(jax.random.key(8), (64, 16), jnp.float32)
    head = jax.random.normal(jax.random.key(9), (16, 32)) * 0.3
    t = jax.random.randint(jax.random.key(10), (64,), 0, 32)
    got = jax.jit(lambda h: blocked_cross_entropy(h, head, t))(h)
    want = reference_xent_sum(h, head, t)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)


def test_losses_dispatch_table_has_blocked_entry():
    from deeplearning4j_tpu.ops import losses
    assert losses.BLOCKED_XENT_BACKEND == "pallas"
    fn = losses.get("blocked_mcxent")
    labels = jnp.eye(8)[jnp.arange(8) % 8]
    h = jax.random.normal(jax.random.key(11), (8, 16))
    head = jax.random.normal(jax.random.key(12), (16, 8)) * 0.3
    via_pair = fn(labels, (h, head))
    logits = (h @ head).astype(jnp.float32)
    via_logits = fn(labels, logits)
    np.testing.assert_allclose(float(via_pair), float(via_logits), rtol=1e-5)


def test_losses_fallback_matches_pallas_backend():
    from deeplearning4j_tpu.ops import losses
    h = jax.random.normal(jax.random.key(13), (45, 16), jnp.float32)
    head = jax.random.normal(jax.random.key(14), (16, 19)) * 0.3
    t = jax.random.randint(jax.random.key(15), (45,), 0, 19)
    a = losses.blocked_token_xent(h, head, t)
    b = losses._blocked_xent_fallback(h, head, t)
    np.testing.assert_allclose(float(a), float(b), rtol=1e-5)


# ------------------------------------------------------------- int8 matmul

def test_quantize_roundtrip_error_bounded():
    w = jax.random.normal(jax.random.key(16), (32, 24)) * 0.1
    qw = quantize(w)
    assert qw.q.dtype == jnp.int8 and qw.scale.shape == (24,)
    # symmetric absmax: per-channel error <= scale/2 (half a quant step)
    err = jnp.abs(dequantize(qw) - w)
    assert bool(jnp.all(err <= qw.scale[None, :] * 0.5 + 1e-7))


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_int8_matmul_forward_parity(dtype):
    w = jax.random.normal(jax.random.key(17), (32, 100)) * 0.05
    qw = quantize(w)
    x = jax.random.normal(jax.random.key(18), (3, 5, 32), dtype)
    got = int8_matmul(x, qw, block_n=64)
    want = reference_int8_matmul(x, qw)
    assert got.dtype == jnp.float32
    _close(got, want, dtype)


def test_int8_matmul_gradient_flows_to_activations_only():
    w = jax.random.normal(jax.random.key(19), (16, 24)) * 0.05
    qw = quantize(w)
    x = jax.random.normal(jax.random.key(20), (7, 16), jnp.float32)
    g1 = jax.grad(lambda x: jnp.sum(jnp.sin(int8_matmul(x, qw))))(x)
    g2 = jax.grad(lambda x: jnp.sum(jnp.sin(reference_int8_matmul(x, qw))))(x)
    _close(g1, g2, jnp.float32)


def test_quantized_tree_drops_f32_ffn_and_decode_agrees():
    from deeplearning4j_tpu.models import transformer as tf
    cfg = tf.TransformerConfig(vocab_size=97, d_model=32, n_heads=2,
                               n_layers=2, max_len=64, dtype=jnp.float32)
    params = tf.init_params(jax.random.key(21), cfg)
    qp = quantize_params_for_decode(params, cfg)
    for lp in qp["layers"]:
        assert "w1" not in lp and "w2" not in lp
        assert lp["w1_q"].q.dtype == jnp.int8
    assert "head_q" in qp
    cache = tf.init_decode_cache(cfg, 2)
    toks = jnp.array([3, 5], jnp.int32)
    lg_f32, _ = tf.decode_step(params, cache, toks, 0, cfg)
    lg_i8, _ = tf.decode_step(qp, cache, toks, 0, cfg)
    assert float(top1_agreement(lg_f32, lg_i8)) == 1.0


# ------------------------------------------------- transformer-level parity

def _tiny_cfg(**kw):
    from deeplearning4j_tpu.models.transformer import TransformerConfig
    return TransformerConfig(vocab_size=101, d_model=32, n_heads=2,
                             n_layers=2, d_ff=64, max_len=128, causal=True,
                             dtype=jnp.float32, **kw)


@pytest.mark.parametrize("variant", [
    {"attention": "fused"},
    {"fused_ln": True},
    {"xent_impl": "blocked", "xent_chunk": 64},
])
def test_transformer_kernel_variants_match_default(variant):
    """Each bench-gated kernel opt-in computes the same loss and gradients
    as the default XLA path (vocab 101 is prime: the blocked variant runs
    the shape-independent streaming schedule, not a lucky divisor)."""
    from deeplearning4j_tpu.models.transformer import (init_params,
                                                       lm_loss_local)
    cfg = _tiny_cfg()
    params = init_params(jax.random.key(22), cfg)
    toks = jax.random.randint(jax.random.key(23), (2, 128), 0, 101)
    tgts = jnp.roll(toks, -1, axis=1)

    def run(c):
        return jax.value_and_grad(
            lambda p: lm_loss_local(p, toks, tgts, c))(params)

    l0, g0 = run(cfg)
    l1, g1 = run(_tiny_cfg(**variant))
    assert abs(float(l0) - float(l1)) < 1e-5
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_near_prime_token_count_streams_through_blocked_xent():
    """The PR-5 zero-weight-padding fallback is gone: a near-prime token
    count now routes to the blocked kernel and still matches the
    unchunked loss exactly."""
    from deeplearning4j_tpu.models.transformer import (init_params,
                                                       lm_head_loss)
    cfg = _tiny_cfg(xent_chunk=64)
    params = init_params(jax.random.key(24), cfg)
    # B*T = 1*127 (prime): the divisor search collapses below chunk//4
    h = jax.random.normal(jax.random.key(25), (1, 127, 32), jnp.float32)
    tgts = jax.random.randint(jax.random.key(26), (1, 127), 0, 101)
    chunked = lm_head_loss(params, h, tgts, cfg)
    full = lm_head_loss(params, h, tgts, _tiny_cfg(xent_chunk=0))
    np.testing.assert_allclose(float(chunked), float(full), rtol=1e-5)


# ----------------------------------------------------------- paged attention

def _paged_case(dtype, B=3, H=4, D=16, ps=5, n_pages=4, seed=0):
    from deeplearning4j_tpu.ops.pallas.paged_attention import (
        paged_attention, reference_paged_attention)

    rng = np.random.default_rng(seed)
    n_phys = B * n_pages + 1
    q = jnp.asarray(rng.standard_normal((B, H, D)), dtype)
    k = jnp.asarray(rng.standard_normal((n_phys, ps, H, D)), dtype)
    v = jnp.asarray(rng.standard_normal((n_phys, ps, H, D)), dtype)
    bt = jnp.asarray(rng.permutation(n_phys - 1)[: B * n_pages]
                     .reshape(B, n_pages), jnp.int32)
    lengths = jnp.asarray([1, ps + 2, n_pages * ps], jnp.int32)[:B]
    return paged_attention, reference_paged_attention, (q, k, v, bt, lengths)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_paged_attention_parity_odd_page_size(dtype):
    """Interpret-mode kernel vs the jnp gather reference at an odd page
    size, including a row whose valid length is 1 (one real K/V entry,
    three fully-masked pages — the running-softmax edge case) and a row
    ending exactly on a page boundary."""
    fn, ref, args = _paged_case(dtype)
    out = fn(*args)
    want = ref(*args)
    assert out.dtype == args[0].dtype
    _close(out, want, dtype)


def test_paged_attention_reads_through_block_table():
    """Permuting the physical pages while permuting the table the same
    way must not change the result — the kernel really addresses K/V
    through the scalar-prefetched table, not by position."""
    fn, ref, (q, k, v, bt, lengths) = _paged_case(jnp.float32, seed=3)
    base = fn(q, k, v, bt, lengths)
    perm = np.random.default_rng(7).permutation(k.shape[0])
    inv = np.argsort(perm)
    k2 = k[perm]
    v2 = v[perm]
    bt2 = jnp.asarray(np.asarray(inv)[np.asarray(bt)], jnp.int32)
    again = fn(q, k2, v2, bt2, lengths)
    _close(again, base, jnp.float32)


def test_paged_attention_registered_behind_autopick_gate():
    """The serving engine may only reach the Pallas candidate through
    the registry, and the registry's gate must refuse it without fresh
    correctness + margin evidence."""
    cand = registry.get("paged_attention", "pallas")
    inc = registry.get("paged_attention", "gather")
    assert inc.source == "xla" and cand.tolerances["max_err"] == 0.05
    rows = [
        {"kernel": "paged_attention", "candidate": "gather",
         "tokens_per_sec": 100.0},
        {"kernel": "paged_attention", "candidate": "pallas",
         "check": {"max_err": 0.001}},
        {"kernel": "paged_attention", "candidate": "pallas",
         "tokens_per_sec": 101.0},
    ]
    pick = registry.autopick("paged_attention", rows, incumbent="gather")
    assert pick.choice == "gather"       # within 2%: no adoption
    rows[-1]["tokens_per_sec"] = 150.0
    pick = registry.autopick("paged_attention", rows, incumbent="gather")
    assert pick.choice == "pallas"       # evidence + margin: adopted


# ------------------------------------------------ paged attention: GQA + int8

@pytest.mark.parametrize("n_kv", [1, 2, 4])
def test_paged_attention_gqa_parity(n_kv):
    """Kernel vs reference when pages carry fewer K/V heads than query
    heads (H=4, Kv in {1, 2, 4}): the in-register head-group broadcast
    must match the gather reference's repeat-heads path."""
    from deeplearning4j_tpu.ops.pallas.paged_attention import (
        paged_attention, reference_paged_attention)
    B, H, D, ps, n_pages = 3, 4, 16, 5, 4
    rng = np.random.default_rng(11)
    n_phys = B * n_pages + 1
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((n_phys, ps, n_kv, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((n_phys, ps, n_kv, D)), jnp.float32)
    bt = jnp.asarray(rng.permutation(n_phys - 1)[: B * n_pages]
                     .reshape(B, n_pages), jnp.int32)
    lengths = jnp.asarray([1, ps + 2, n_pages * ps], jnp.int32)
    out = paged_attention(q, k, v, bt, lengths)
    want = reference_paged_attention(q, k, v, bt, lengths)
    _close(out, want, jnp.float32)


def _paged_int8_case(n_kv=4, B=3, H=4, D=16, ps=5, n_pages=4, seed=0):
    from deeplearning4j_tpu.ops.pallas import kv_quant
    from deeplearning4j_tpu.ops.pallas.paged_attention import (
        paged_attention_int8, reference_paged_attention_int8)
    rng = np.random.default_rng(seed)
    n_phys = B * n_pages + 1
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    kf = jnp.asarray(rng.standard_normal((n_phys, ps, n_kv, D)), jnp.float32)
    vf = jnp.asarray(rng.standard_normal((n_phys, ps, n_kv, D)), jnp.float32)
    s0 = jnp.full((n_phys, n_kv), kv_quant.neutral_scale(jnp.int8))
    k, ks = kv_quant.requantize_pool(kf, s0, jnp.int8)
    v, vs = kv_quant.requantize_pool(vf, s0, jnp.int8)
    bt = jnp.asarray(rng.permutation(n_phys - 1)[: B * n_pages]
                     .reshape(B, n_pages), jnp.int32)
    lengths = jnp.asarray([1, ps + 2, n_pages * ps], jnp.int32)[:B]
    return (paged_attention_int8, reference_paged_attention_int8,
            (q, k, v, ks, vs, bt, lengths))


@pytest.mark.parametrize("n_kv", [2, 4])
def test_paged_attention_int8_kernel_matches_reference(n_kv):
    """The in-kernel per-page dequantize (interpret mode, so the real
    kernel body runs on CPU) must match the dequantize-whole-pool jnp
    reference — which IS the engine's quantized parity path."""
    fn, ref, args = _paged_int8_case(n_kv=n_kv)
    out = fn(*args)
    want = ref(*args)
    assert out.dtype == args[0].dtype
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               atol=2e-4, rtol=2e-4)


def test_paged_attention_int8_tracks_float_within_quant_band():
    """Quantize-then-attend stays inside the kind's registered numeric
    band (max_err 0.05) of full-precision attention over the ORIGINAL
    float pool content — the error budget autopick holds it to."""
    from deeplearning4j_tpu.ops.pallas import kv_quant
    from deeplearning4j_tpu.ops.pallas.paged_attention import (
        reference_paged_attention, reference_paged_attention_int8)
    B, H, D, ps, n_pages = 3, 4, 16, 5, 4
    rng = np.random.default_rng(5)
    n_phys = B * n_pages + 1
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    kf = jnp.asarray(rng.standard_normal((n_phys, ps, H, D)), jnp.float32)
    vf = jnp.asarray(rng.standard_normal((n_phys, ps, H, D)), jnp.float32)
    s0 = jnp.full((n_phys, H), kv_quant.neutral_scale(jnp.int8))
    k, ks = kv_quant.requantize_pool(kf, s0, jnp.int8)
    v, vs = kv_quant.requantize_pool(vf, s0, jnp.int8)
    bt = jnp.asarray(rng.permutation(n_phys - 1)[: B * n_pages]
                     .reshape(B, n_pages), jnp.int32)
    lengths = jnp.asarray([1, ps + 2, n_pages * ps], jnp.int32)
    a = reference_paged_attention_int8(q, k, v, ks, vs, bt, lengths)
    b = reference_paged_attention(q, kf, vf, bt, lengths)
    assert float(jnp.max(jnp.abs(a - b))) < 0.05


def test_paged_attention_int8_gate_needs_agreement_floor():
    """int8 KV adoption requires the top-1 agreement floor on top of
    margin + max_err — a fast kernel that flips tokens stays dropped."""
    cand = registry.get("paged_attention_int8", "pallas_int8")
    inc = registry.get("paged_attention_int8", "gather_int8")
    assert inc.source == "xla"
    assert cand.tolerances["min"]["top1_agree"] == 0.999
    rows = [
        {"kernel": "paged_attention_int8", "candidate": "gather_int8",
         "tokens_per_sec": 100.0},
        {"kernel": "paged_attention_int8", "candidate": "pallas_int8",
         "check": {"max_err": 0.001, "top1_agree": 0.99}},   # below floor
        {"kernel": "paged_attention_int8", "candidate": "pallas_int8",
         "tokens_per_sec": 200.0},
    ]
    pick = registry.autopick("paged_attention_int8", rows,
                             incumbent="gather_int8")
    assert pick.choice == "gather_int8"
    rows[1]["check"]["top1_agree"] = 1.0
    pick = registry.autopick("paged_attention_int8", rows,
                             incumbent="gather_int8")
    assert pick.choice == "pallas_int8"
