"""Metrics: counters, gauges, and bucketed timing histograms.

Grown from the seed ``parallel/observe.py`` registry (counters + flat timer
lists) into the production surface: every timer is a ``Histogram`` with
Prometheus-style cumulative buckets plus a bounded window of raw values for
percentile snapshots (p50/p95/p99), and the whole registry renders to
Prometheus text exposition format (``to_prometheus``) alongside the JSON
``snapshot``.

All mutation goes through the registry lock; the seed's
``StepTimer.iteration_done`` wrote ``registry.timers[name].append(...)``
directly, bypassing it — that path is now the locked ``observe_time``.
When observability is disabled (``core.disable()``) every mutator returns
before taking the lock.
"""

from __future__ import annotations

import os
import threading
import time
from collections import defaultdict, deque
from typing import Any, Iterable

from . import core

# Default buckets for timings in seconds: 0.5ms .. 60s, roughly 2.5x steps —
# wide enough for a CPU-test microstep and a pod-slice BERT step alike.
DEFAULT_TIME_BUCKETS: tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

# Raw-value window per histogram for percentile estimation.  Percentiles are
# over the most recent WINDOW observations (a ring buffer), which is what a
# step-time dashboard wants anyway; bucket counts/sum/count remain exact
# over the full lifetime.  Evicted observations are counted per histogram
# and surfaced as the synthetic ``metrics.dropped_samples`` counter in
# ``snapshot()``/``to_prometheus()`` so a long run can see how much raw
# history its percentiles stand on.  Env-tunable for long soak runs.
ENV_HIST_WINDOW = "DL4J_TPU_HIST_WINDOW"
WINDOW = max(16, int(os.environ.get(ENV_HIST_WINDOW, "4096") or "4096"))


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return float("nan")
    idx = min(len(sorted_vals) - 1, max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class Histogram:
    """Cumulative-bucket histogram + bounded raw-value window.

    Not internally locked: the owning registry serializes access.
    """

    __slots__ = ("buckets", "bucket_counts", "count", "total", "values",
                 "dropped")

    def __init__(self, buckets: Iterable[float] = DEFAULT_TIME_BUCKETS,
                 window: int | None = None):
        self.buckets = tuple(sorted(buckets))
        self.bucket_counts = [0] * len(self.buckets)  # cumulative on render
        self.count = 0
        self.total = 0.0
        self.values: deque[float] = deque(maxlen=window or WINDOW)
        self.dropped = 0  # raw values evicted from the percentile window

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += value
        if len(self.values) == self.values.maxlen:
            self.dropped += 1
        self.values.append(value)
        for i, ub in enumerate(self.buckets):
            if value <= ub:
                self.bucket_counts[i] += 1
                break

    def cumulative_buckets(self) -> list[tuple[float, int]]:
        """[(upper_bound, cumulative_count)] — +Inf row is implicit
        (``count``)."""
        out, acc = [], 0
        for ub, c in zip(self.buckets, self.bucket_counts):
            acc += c
            out.append((ub, acc))
        return out

    def summary(self) -> dict[str, float]:
        vals = sorted(self.values)
        return {
            "count": self.count,
            "total_s": self.total,
            "mean_s": self.total / self.count if self.count else 0.0,
            "p50_s": _percentile(vals, 0.50),
            "p95_s": _percentile(vals, 0.95),
            "p99_s": _percentile(vals, 0.99),
            "max_s": vals[-1] if vals else float("nan"),
            "dropped": self.dropped,
        }


class _Timer:
    """``with registry.time(name):`` — observes elapsed seconds on exit."""

    __slots__ = ("registry", "name", "t0")

    def __init__(self, registry: "MetricsRegistry", name: str):
        self.registry = registry
        self.name = name

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.registry.observe_time(self.name, time.perf_counter() - self.t0)
        return False


def _prom_name(name: str) -> str:
    """Dotted registry names -> Prometheus metric names."""
    return "".join(c if (c.isalnum() or c in "_:") else "_" for c in name)


def _prom_float(v: float) -> str:
    if v != v:  # NaN
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    return repr(float(v))


class MetricsRegistry:
    """Process-wide named counters/gauges/timing-histograms."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: dict[str, float] = defaultdict(float)
        self.gauges: dict[str, float] = {}
        self.timers: dict[str, Histogram] = {}
        # Called as fn(kind, name, value) after counter/gauge mutation,
        # outside the registry lock (the flight recorder takes its own lock).
        # Timing observations are deliberately not forwarded — too hot.
        self._listeners: list = []

    def add_listener(self, fn) -> None:
        self._listeners.append(fn)

    def _notify(self, kind: str, name: str, value: float) -> None:
        for fn in self._listeners:
            try:
                fn(kind, name, value)
            except Exception:
                pass

    # ------------------------------------------------------------- mutation
    def increment(self, name: str, by: float = 1.0) -> None:
        if not core.enabled():
            return
        with self._lock:
            self.counters[name] += by
        if self._listeners:
            self._notify("counter", name, by)

    def gauge(self, name: str, value: float) -> None:
        if not core.enabled():
            return
        with self._lock:
            self.gauges[name] = value
        if self._listeners:
            self._notify("gauge", name, value)

    def observe_time(self, name: str, seconds: float,
                     buckets: Iterable[float] | None = None) -> None:
        """Record one timing observation under the registry lock (the only
        sanctioned way in — no caller touches ``timers[...]`` directly)."""
        if not core.enabled():
            return
        with self._lock:
            h = self.timers.get(name)
            if h is None:
                h = self.timers[name] = Histogram(buckets or DEFAULT_TIME_BUCKETS)
            h.observe(seconds)

    def observe_many(self, name: str, values: Iterable[float],
                     buckets: Iterable[float] | None = None) -> None:
        """Record a batch of timing observations under ONE lock acquisition —
        the resolution-point companion to ``observe_time``: the async trainer
        publishes a whole window of amortized step times at once when it
        fences, and should not take the registry lock per entry."""
        if not core.enabled():
            return
        with self._lock:
            h = self.timers.get(name)
            if h is None:
                h = self.timers[name] = Histogram(buckets or DEFAULT_TIME_BUCKETS)
            for v in values:
                h.observe(v)

    def time(self, name: str):
        """Context manager timing its body into the ``name`` histogram."""
        if not core.enabled():
            return core.NOOP_SPAN
        return _Timer(self, name)

    def reset(self) -> None:
        """Drop all recorded state (test isolation for the global
        ``METRICS`` singleton)."""
        with self._lock:
            self.counters.clear()
            self.gauges.clear()
            self.timers.clear()

    # ------------------------------------------------------------- export
    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            counters = dict(self.counters)
            # Synthetic render-time counter: raw observations evicted from
            # percentile windows.  Computed here (not incremented from
            # inside Histogram.observe, which already runs under this
            # non-reentrant lock) so it costs nothing on the observe path.
            dropped = sum(h.dropped for h in self.timers.values())
            if dropped:
                counters["metrics.dropped_samples"] = float(dropped)
            return {
                "counters": counters,
                "gauges": dict(self.gauges),
                "timers": {k: h.summary() for k, h in self.timers.items()
                           if h.count},
            }

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (0.0.4).

        Counters get a ``_total`` suffix (convention), timers render as
        native histograms in seconds (``_seconds_bucket/_sum/_count``).
        """
        lines: list[str] = []
        with self._lock:
            counters = dict(self.counters)
            dropped = sum(h.dropped for h in self.timers.values())
            if dropped:
                counters["metrics.dropped_samples"] = float(dropped)
            for name in sorted(counters):
                pn = _prom_name(name)
                if not pn.endswith("_total"):
                    pn += "_total"
                lines.append(f"# TYPE {pn} counter")
                lines.append(f"{pn} {_prom_float(counters[name])}")
            for name in sorted(self.gauges):
                pn = _prom_name(name)
                lines.append(f"# TYPE {pn} gauge")
                lines.append(f"{pn} {_prom_float(self.gauges[name])}")
            for name in sorted(self.timers):
                h = self.timers[name]
                pn = _prom_name(name)
                if not pn.endswith("_seconds"):
                    pn += "_seconds"
                lines.append(f"# TYPE {pn} histogram")
                for ub, acc in h.cumulative_buckets():
                    lines.append(f'{pn}_bucket{{le="{_prom_float(ub)}"}} {acc}')
                lines.append(f'{pn}_bucket{{le="+Inf"}} {h.count}')
                lines.append(f"{pn}_sum {_prom_float(h.total)}")
                lines.append(f"{pn}_count {h.count}")
        return "\n".join(lines) + "\n"


METRICS = MetricsRegistry()


class StepTimer:
    """IterationListener recording per-iteration wall time and score into
    the registry — via the locked ``observe_time`` path (the seed version
    appended to ``registry.timers[...]`` directly, racing ``snapshot``)."""

    def __init__(self, registry: MetricsRegistry = METRICS, name: str = "train_step"):
        self.registry = registry
        self.name = name
        self._last = None

    def iteration_done(self, model, iteration: int) -> None:
        now = time.perf_counter()
        if self._last is not None:
            self.registry.observe_time(self.name, now - self._last)
        self._last = now
        self.registry.increment(f"{self.name}.iterations")
        if hasattr(model, "score"):
            try:
                self.registry.gauge(f"{self.name}.score", float(model.score()))
            except Exception:
                pass
