"""Perf smoke: bounded-recompile guard for the async trainer hot loop.

Runs a 30-step CPU fit whose batch sizes are deliberately ragged and
asserts the steady-state number of XLA compilations equals the number of
padding *buckets* actually used (`train_step.recompile` counter) — the
regression this guards against is the pre-bucketing behavior where every
distinct ragged shape silently compiled a fresh step program.

The expected bucket set is an INDEPENDENT reimplementation of the
trainer's ladder (powers of two rounded up to the dp width, capped at the
nominal batch): if someone changes the trainer's bucketing they must
consciously change this file too, not just watch a counter follow along.

Wired as a fast tier-1 test (`tests/test_perf_smoke.py`); also runnable
standalone: `python tools/perf_smoke.py` prints one JSON line.
"""

from __future__ import annotations

import json
import math
import sys

# the ragged pattern: first size fixes the nominal bucket cap
RAGGED_SIZES = [32, 31, 17, 9, 23, 13, 32, 5, 29, 11]


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def expected_buckets(sizes, n_dp: int) -> set[int]:
    """Reference bucket ladder (kept independent of the trainer's code)."""
    nominal = _round_up(sizes[0], n_dp)
    out = set()
    for n in sizes:
        if n >= nominal:
            out.add(_round_up(n, n_dp))
        else:
            out.add(min(_round_up(1 << math.ceil(math.log2(n)), n_dp), nominal))
    return out


def run(steps: int = 30) -> dict:
    import numpy as np

    from deeplearning4j_tpu import observability
    from deeplearning4j_tpu.analysis.runtime import guard_mode
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.observability import METRICS
    from deeplearning4j_tpu.optimize import transforms as T
    from deeplearning4j_tpu.parallel import DataParallelTrainer

    observability.enable()
    METRICS.reset()

    rng = np.random.default_rng(0)
    w_true = rng.normal(size=(6, 1))

    def batches():
        for k in range(steps):
            n = RAGGED_SIZES[k % len(RAGGED_SIZES)]
            x = rng.normal(size=(n, 6)).astype(np.float32)
            y = (x @ w_true).astype(np.float32)
            yield DataSet(x, y)

    def loss_fn(p, x, y, key=None):
        return ((x @ p["w"] - y) ** 2).mean()

    trainer = DataParallelTrainer(loss_fn, T.sgd_lr(0.05))
    params = {"w": np.zeros((6, 1), np.float32)}
    state, losses = trainer.fit(trainer.init_state(params), batches())

    snap = METRICS.snapshot()["counters"]
    recompiles = int(snap.get("train_step.recompile", 0))
    n_buckets = len(expected_buckets(
        [RAGGED_SIZES[k % len(RAGGED_SIZES)] for k in range(steps)],
        trainer.n_dp))
    result = {
        "steps": int(snap.get("train_step.iterations", 0)),
        "recompiles": recompiles,
        "expected_buckets": n_buckets,
        "n_dp": trainer.n_dp,
        # fit's steady state ran under jax.transfer_guard(<mode>): any
        # implicit host<->device transfer would have failed the run
        "transfer_guard": guard_mode() or "off",
        "losses_finite": all(math.isfinite(l) for l in losses),
        "final_loss": losses[-1] if losses else None,
    }
    assert result["steps"] == steps, f"ran {result['steps']}/{steps} steps"
    assert result["losses_finite"], "non-finite loss in smoke run"
    assert recompiles == n_buckets, (
        f"{recompiles} recompiles != {n_buckets} buckets — "
        "per-shape recompilation is back (or the ladder changed; "
        "update expected_buckets deliberately)")
    return result


def main() -> int:
    print(json.dumps(run()))
    return 0


if __name__ == "__main__":
    import os
    import pathlib

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "--xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8")
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    sys.exit(main())
