"""Cluster provisioning — the TPU-native analog of the reference's AWS
module (``deeplearning4j-aws``): ``Ec2BoxCreator.java:19,59`` (create spot/
on-demand instances), ``provision/ClusterSetup.java:24`` +
``HostProvisioner`` (SSH fan-out setup), and the YARN ``Client`` launch
path.

There is no cloud reachable from this environment, so the module does what
those classes actually owe the framework: given a cluster spec, produce the
exact commands/scripts that create a TPU pod slice and bring the training
job up on every host — creation command, per-host bootstrap, and a
coordinated multi-host launch with the ``jax.distributed`` env contract
(``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``) that
``parallel.mesh.initialize_multihost`` consumes.  Everything is returned as
data (and optionally written as a shell script) so it is testable offline
and runnable verbatim where a cloud is present.
"""

from __future__ import annotations

import dataclasses
import shlex
import subprocess
from pathlib import Path

__all__ = ["PodSliceSpec", "PodSliceProvisioner"]

# The accelerator-type numeric suffix counts CHIPS for v5e (v5litepod-N)
# but TENSORCORES (2 per chip) for v2/v3/v4/v5p; every generation here
# packs 4 chips per host.
_SUFFIX_COUNTS_CHIPS = {"v5litepod"}
_CHIPS_PER_HOST = 4


@dataclasses.dataclass(frozen=True)
class PodSliceSpec:
    """What ``Ec2BoxCreator``'s (ami, size, numBoxes) tuple becomes on TPU:
    a named slice of an accelerator type in a zone."""

    name: str = "dl4j-tpu-slice"
    accelerator_type: str = "v5litepod-64"   # BASELINE.md scaling target
    zone: str = "us-west4-a"
    runtime_version: str = "tpu-ubuntu2204-base"
    project: str | None = None
    spot: bool = False                        # Ec2BoxCreator spot parity
    coordinator_port: int = 8476

    @property
    def generation(self) -> str:
        return self.accelerator_type.rsplit("-", 1)[0]

    @property
    def n_chips(self) -> int:
        suffix = int(self.accelerator_type.rsplit("-", 1)[1])
        if self.generation in _SUFFIX_COUNTS_CHIPS:
            return suffix
        return max(1, suffix // 2)       # core-counted generations

    @property
    def n_hosts(self) -> int:
        return max(1, self.n_chips // _CHIPS_PER_HOST)


class PodSliceProvisioner:
    """Renders the create/bootstrap/launch command set for a pod slice."""

    def __init__(self, spec: PodSliceSpec):
        self.spec = spec

    # -- creation (Ec2BoxCreator.create parity) -------------------------
    def create_command(self) -> list[str]:
        s = self.spec
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "create", s.name,
               f"--zone={s.zone}",
               f"--accelerator-type={s.accelerator_type}",
               f"--version={s.runtime_version}"]
        if s.project:
            cmd.append(f"--project={s.project}")
        if s.spot:
            cmd.append("--spot")
        return cmd

    def delete_command(self) -> list[str]:
        s = self.spec
        return ["gcloud", "compute", "tpus", "tpu-vm", "delete", s.name,
                f"--zone={s.zone}", "--quiet"]

    # -- per-host bootstrap (HostProvisioner parity) --------------------
    def bootstrap_command(self, repo_url: str,
                          workdir: str = "~/deeplearning4j_tpu") -> str:
        """What ``HostProvisioner`` uploads+runs over SSH: fetch the
        framework and its deps onto every host."""
        return (f"git clone {shlex.quote(repo_url)} {workdir} 2>/dev/null "
                f"|| git -C {workdir} pull && "
                f"pip install -U jax[tpu] flax optax orbax-checkpoint")

    def ssh_all_command(self, remote_cmd: str) -> list[str]:
        s = self.spec
        return ["gcloud", "compute", "tpus", "tpu-vm", "ssh", s.name,
                f"--zone={s.zone}", "--worker=all",
                f"--command={remote_cmd}"]

    # -- coordinated launch (ClusterSetup + jax.distributed contract) ----
    def launch_env(self, process_id: int, coordinator_host: str) -> dict[str, str]:
        """Per-host env for ``initialize_multihost`` (the Akka-seed-join
        replacement): coordinator on host 0, one process per host."""
        s = self.spec
        return {
            "JAX_COORDINATOR_ADDRESS": f"{coordinator_host}:{s.coordinator_port}",
            "JAX_NUM_PROCESSES": str(s.n_hosts),
            "JAX_PROCESS_ID": str(process_id),
        }

    def launch_command(self, train_argv: str, coordinator_host: str,
                       workdir: str = "~/deeplearning4j_tpu") -> str:
        """One command runnable via ``--worker=all``: each host derives its
        process id from the TPU metadata worker index and starts the same
        program (SPMD single-controller-per-host)."""
        s = self.spec
        env = " ".join(
            f"{k}={v}" for k, v in self.launch_env(0, coordinator_host).items()
            if k != "JAX_PROCESS_ID")
        return (f"cd {workdir} && {env} "
                "JAX_PROCESS_ID=$(curl -s -H 'Metadata-Flavor: Google' "
                "'http://metadata/computeMetadata/v1/instance/attributes/"
                "agent-worker-number') "
                f"python {train_argv}")

    # -- execution (ClusterSetup.java:24 actually provisions) ------------

    def describe_ip_command(self) -> list[str]:
        s = self.spec
        return ["gcloud", "compute", "tpus", "tpu-vm", "describe", s.name,
                f"--zone={s.zone}",
                "--format=value(networkEndpoints[0].ipAddress)"]

    def apply(self, repo_url: str, train_argv: str, *, dry_run: bool = True,
              coordinator_host: str | None = None,
              timeout_s: float = 1800.0) -> list[dict]:
        """EXECUTE the provisioning sequence — create the slice, bootstrap
        every host, resolve the coordinator IP, launch everywhere — the way
        the reference's ``ClusterSetup``/``HostProvisioner`` actually SSH
        into boxes rather than printing commands.  ``dry_run`` (the
        default) returns the resolved command list without running
        anything; pass ``dry_run=False`` where a cloud and ``gcloud``
        exist.  Returns one ``{"step", "cmd", "rc", "stdout"}`` record per
        command (``rc`` is None under dry-run); raises on the first
        failing step, since later steps depend on earlier ones.  A step
        that exceeds ``timeout_s`` raises a ``RuntimeError`` naming the
        step with the records-so-far attached as ``err.records`` (a
        half-created slice keeps its audit trail)."""
        records = []

        def run(step: str, cmd: list[str]) -> str:
            rec = {"step": step, "cmd": cmd, "rc": None, "stdout": ""}
            records.append(rec)
            if dry_run:
                return ""
            try:
                proc = subprocess.run(cmd, capture_output=True, text=True,
                                      timeout=timeout_s)
            except subprocess.TimeoutExpired as e:
                # a timed-out create/bootstrap leaves a HALF-CREATED slice
                # behind: name the step and carry the audit trail so the
                # caller can tear down exactly what was attempted
                err = RuntimeError(
                    f"provision step {step!r} timed out after "
                    f"{timeout_s:.0f}s — the slice may be half-created; "
                    "inspect err.records and run teardown()")
                err.records = records
                raise err from e
            rec["rc"] = proc.returncode
            rec["stdout"] = proc.stdout.strip()
            if proc.returncode != 0:
                raise RuntimeError(
                    f"provision step {step!r} failed rc={proc.returncode}: "
                    f"{proc.stderr[-500:]}")
            return rec["stdout"]

        run("create", self.create_command())
        run("bootstrap", self.ssh_all_command(self.bootstrap_command(repo_url)))
        coord = coordinator_host or run("resolve_coordinator",
                                        self.describe_ip_command())
        if not coord:
            if dry_run:
                coord = "$COORD"     # placeholder, as in the rendered script
            else:
                # launching a pod against an empty coordinator address hangs
                # every host in distributed init with no error — fail here
                raise RuntimeError(
                    "coordinator IP resolve returned empty (slice endpoint "
                    "not yet populated?) — refusing to launch")
        run("launch", self.ssh_all_command(
            self.launch_command(train_argv, coord)))
        return records

    def teardown(self, *, dry_run: bool = True,
                 timeout_s: float = 1800.0) -> dict:
        """EXECUTE slice deletion (the Kill-side symmetry of ``apply``)."""
        cmd = self.delete_command()
        rec = {"step": "delete", "cmd": cmd, "rc": None, "stdout": ""}
        if not dry_run:
            try:
                proc = subprocess.run(cmd, capture_output=True, text=True,
                                      timeout=timeout_s)
            except subprocess.TimeoutExpired as e:
                err = RuntimeError(
                    f"teardown step 'delete' timed out after {timeout_s:.0f}s "
                    "— the slice may still exist; inspect err.records")
                err.records = [rec]
                raise err from e
            rec["rc"] = proc.returncode
            rec["stdout"] = proc.stdout.strip()
            if proc.returncode != 0:
                raise RuntimeError(
                    f"teardown failed rc={proc.returncode}: "
                    f"{proc.stderr[-500:]}")
        return rec

    # -- one-file artifact ----------------------------------------------
    def render_script(self, repo_url: str, train_argv: str,
                      coordinator_host: str = "$(gcloud compute tpus tpu-vm "
                      "describe {name} --zone={zone} --format="
                      "'value(networkEndpoints[0].ipAddress)')") -> str:
        s = self.spec
        coord = coordinator_host.format(name=s.name, zone=s.zone)
        lines = [
            "#!/usr/bin/env bash",
            "# Auto-generated pod-slice provisioning script "
            f"({s.accelerator_type}, {s.n_hosts} hosts, {s.n_chips} chips)",
            "set -euo pipefail",
            "",
            "# 1. create the slice",
            shlex.join(self.create_command()),
            "",
            "# 2. bootstrap every host",
            shlex.join(self.ssh_all_command(self.bootstrap_command(repo_url))),
            "",
            "# 3. resolve coordinator (host 0) and launch everywhere",
            f'COORD={coord}',
            # manual quoting: $COORD must expand in the OUTER shell, so the
            # --command payload is double-quoted, not shlex-single-quoted
            # $COORD expands on the operator machine; the $(curl ...) worker-
            # index lookup is escaped so it runs on each TPU host instead
            (shlex.join(self.ssh_all_command("")[:-1])
             + ' "--command=' + self.launch_command(train_argv, "$COORD")
             .replace('"', '\\"').replace("$(curl", "\\$(curl") + '"'),
            "",
        ]
        return "\n".join(lines)

    def write_script(self, path: str | Path, repo_url: str,
                     train_argv: str) -> Path:
        path = Path(path)
        path.write_text(self.render_script(repo_url, train_argv))
        path.chmod(0o755)
        return path
