"""NLP stack tests (mirror of the reference's Word2VecTests / GloveTest /
ParagraphVectorsTest / tokenizer & vectorizer tests / WordVectorSerializerTest
— small corpus fixtures, semantic-sanity assertions)."""

import numpy as np
import pytest

from deeplearning4j_tpu.text import (
    BagOfWordsVectorizer,
    CollectionSentenceIterator,
    DefaultTokenizerFactory,
    Glove,
    Huffman,
    LabelAwareListSentenceIterator,
    LineSentenceIterator,
    ParagraphVectors,
    TfidfVectorizer,
    VocabCache,
    Word2Vec,
    build_vocab,
)
from deeplearning4j_tpu.text.tokenization import (
    CommonPreprocessor,
    DefaultTokenizer,
    NGramTokenizer,
)
from deeplearning4j_tpu.text.serializer import (
    load_google_binary,
    load_into_word2vec,
    load_txt,
    save_google_binary,
    save_txt,
    save_word2vec,
)

# A tiny corpus with two clear topic clusters (fruit vs vehicles).
CORPUS = [
    "the apple is a sweet fruit",
    "banana is a yellow fruit and the banana is sweet",
    "orange fruit is sweet and orange is juicy",
    "apple and banana and orange are fruit",
    "fruit salad has apple banana orange",
    "the car drives on the road",
    "a truck is a big car on the road",
    "the bus drives people on the road",
    "car truck and bus are vehicles on the road",
    "vehicles like car and bus drive fast",
] * 8


def test_tokenizer_and_preprocessors():
    t = DefaultTokenizer("Hello, World! 42 foo-bar")
    assert t.get_tokens() == ["Hello,", "World!", "42", "foo-bar"]
    t2 = DefaultTokenizer("Hello, World!", CommonPreprocessor())
    assert t2.get_tokens() == ["hello", "world"]
    ng = NGramTokenizer("a b c", n=2)
    assert "a b" in ng.get_tokens() and "b c" in ng.get_tokens()


def test_sentence_iterators(tmp_path):
    it = CollectionSentenceIterator(["s one", "s two"])
    assert list(it) == ["s one", "s two"]
    it.pre_processor = str.upper
    assert list(it) == ["S ONE", "S TWO"]
    p = tmp_path / "corpus.txt"
    p.write_text("line one\n\nline two\n")
    assert list(LineSentenceIterator(p)) == ["line one", "line two"]
    la = LabelAwareListSentenceIterator(["a", "b"], ["L0", "L1"])
    la.next_sentence()
    assert la.current_label() == "L0"


def test_vocab_build_and_prune():
    cache = build_vocab(CORPUS, DefaultTokenizerFactory(CommonPreprocessor()),
                        min_word_frequency=5)
    assert "fruit" in cache and "car" in cache
    assert cache.index_of("nonexistent") == -1
    # most frequent word gets index 0
    counts = cache.counts_array()
    assert counts[0] == counts.max()


def test_native_vocab_matches_python():
    tf = DefaultTokenizerFactory(CommonPreprocessor())
    fast = build_vocab(CORPUS, tf, min_word_frequency=1, use_native=True)
    slow = build_vocab(CORPUS, tf, min_word_frequency=1, use_native=False)
    assert set(fast.words()) == set(slow.words())
    for w in slow.words():
        assert fast.count_of(w) == slow.count_of(w), w


def test_huffman_codes():
    cache = build_vocab(CORPUS, DefaultTokenizerFactory(CommonPreprocessor()))
    h = Huffman(cache)
    h.build()
    # Kraft equality for a full binary tree: sum 2^-len == 1
    total = sum(2.0 ** -len(cache.word_for(w).codes) for w in cache.words())
    assert abs(total - 1.0) < 1e-9
    # frequent words get shorter codes
    ws = cache.words()
    assert len(cache.word_for(ws[0]).codes) <= len(cache.word_for(ws[-1]).codes)
    codes, points, lengths = h.code_arrays()
    assert codes.shape == points.shape
    assert lengths.max() == h.max_code_length


def test_word2vec_hs_learns_topics():
    model = Word2Vec(CORPUS, layer_size=32, window=3, iterations=8,
                     min_word_frequency=3, seed=7)
    model.fit()
    assert model.has_word("apple") and model.has_word("car")
    # within-topic similarity beats cross-topic
    fruit_sim = model.similarity("apple", "banana")
    cross_sim = model.similarity("apple", "road")
    assert fruit_sim > cross_sim, (fruit_sim, cross_sim)
    assert model.get_word_vector("apple").shape == (32,)
    near = model.words_nearest("car", n=5)
    assert len(near) == 5 and "car" not in near


def test_word2vec_negative_sampling():
    model = Word2Vec(CORPUS, layer_size=32, window=3, iterations=8,
                     min_word_frequency=3, negative=5,
                     use_hierarchic_softmax=False, seed=7)
    model.fit()
    assert model.similarity("banana", "orange") > model.similarity("banana", "bus")


def test_word2vec_subsampling_runs():
    model = Word2Vec(CORPUS, layer_size=16, window=2, iterations=2,
                     sample=1e-3, seed=3)
    model.fit()
    assert np.all(np.isfinite(np.asarray(model.syn0)))


def test_serializer_roundtrips(tmp_path):
    words = ["alpha", "beta", "gamma"]
    vecs = np.random.default_rng(0).random((3, 8)).astype(np.float32)
    save_txt(words, vecs, tmp_path / "v.txt")
    w2, v2 = load_txt(tmp_path / "v.txt")
    assert w2 == words
    np.testing.assert_allclose(v2, vecs, rtol=1e-4)
    save_google_binary(words, vecs, tmp_path / "v.bin")
    w3, v3 = load_google_binary(tmp_path / "v.bin")
    assert w3 == words
    np.testing.assert_allclose(v3, vecs)


def test_word2vec_save_load_query(tmp_path):
    model = Word2Vec(CORPUS, layer_size=16, iterations=2, min_word_frequency=3)
    model.fit()
    save_word2vec(model, tmp_path / "w2v.bin", binary=True)
    loaded = load_into_word2vec(tmp_path / "w2v.bin", binary=True)
    np.testing.assert_allclose(loaded.get_word_vector("fruit"),
                               model.get_word_vector("fruit"), rtol=1e-5)


def test_glove_learns_topics():
    model = Glove(CORPUS, layer_size=24, window=5, iterations=30,
                  min_word_frequency=3, seed=5)
    model.fit()
    assert model.losses[-1] < model.losses[0]
    assert model.similarity("apple", "banana") > model.similarity("apple", "road")


def test_paragraph_vectors():
    labels = [f"DOC_{i}" for i in range(len(CORPUS))]
    model = ParagraphVectors(CORPUS, labels, layer_size=24, window=3,
                             iterations=6, min_word_frequency=3, seed=11)
    model.fit()
    # doc 0 (fruit) should be nearer doc 1 (fruit) than doc 5 (vehicles)
    assert model.doc_similarity("DOC_0", "DOC_1") > model.doc_similarity("DOC_0", "DOC_5")
    vec = model.infer_vector("sweet apple banana fruit")
    assert vec.shape == (24,) and np.all(np.isfinite(vec))


def test_bow_and_tfidf():
    docs = ["apple banana apple", "car road car car", "apple car"]
    bow = BagOfWordsVectorizer()
    x = bow.fit_transform(docs)
    assert x.shape == (3, len(bow.vocab))
    assert x[0, bow.vocab.index_of("apple")] == 2.0
    tfidf = TfidfVectorizer()
    xt = tfidf.fit_transform(docs)
    # 'apple' appears in 2/3 docs; within doc0 tf=2/3
    assert xt.shape == x.shape
    assert np.all(np.isfinite(xt))
    ds = bow.vectorize(docs, [0, 1, 0])
    assert ds.num_outcomes() == 2


def test_native_skipgram_pairs_match_python_counts():
    from deeplearning4j_tpu.native import runtime as native_rt
    if native_rt.lib() is None:
        pytest.skip("native lib unavailable")
    sents = [np.array([0, 1, 2, 3, 4], np.int32), np.array([5, 6, 7], np.int32)]
    out = native_rt.skipgram_pairs(sents, window=2, seed=123)
    assert out is not None
    centers, contexts = out
    assert centers.shape == contexts.shape and centers.size > 0
    # no pair crosses a sentence boundary
    first = set(range(5))
    for c, x in zip(centers.tolist(), contexts.tolist()):
        assert (c in first) == (x in first)


def test_native_cooccurrence_matches_python():
    """The C++ co-occurrence accumulator computes exactly the Python
    fallback's window-weighted counts (skipped when the native lib is
    unavailable)."""
    import numpy as np
    import pytest as _pytest

    from deeplearning4j_tpu.native import runtime as native_rt

    sent_idx = [np.array([0, 1, 2, 1, 3], np.int32),
                np.array([2, 2, 0], np.int32)]
    native = native_rt.cooccurrence(sent_idx, window=2)
    if native is None:
        _pytest.skip("native host runtime not built")
    rows, cols, vals = native

    from collections import defaultdict
    want = defaultdict(float)
    for idx in sent_idx:
        for pos, wi in enumerate(idx):
            for off in range(1, 3):
                j = pos + off
                if j >= len(idx):
                    break
                want[(int(wi), int(idx[j]))] += 1.0 / off
                want[(int(idx[j]), int(wi))] += 1.0 / off

    got = {(int(r), int(c)): float(v) for r, c, v in zip(rows, cols, vals)}
    assert set(got) == set(want)
    for k in want:
        assert abs(got[k] - want[k]) < 1e-6, (k, got[k], want[k])
