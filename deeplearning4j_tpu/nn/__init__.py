"""L1 — core NN runtime: configs, layers, the MultiLayerNetwork container.

TPU-native re-design of the reference's ``deeplearning4j-core/.../nn`` tree
(SURVEY.md §1 L1).  Layers are pure ``init(rng) -> params`` /
``apply(params, x, ...) -> y`` modules over jnp pytrees; the container jits
whole train steps; autodiff replaces the hand-written delta chains.
"""

from .conf import (
    ConfOverride,
    LayerConfig,
    MultiLayerConfiguration,
    NeuralNetConfiguration,
)
from .multilayer import MultiLayerNetwork

__all__ = [
    "ConfOverride",
    "LayerConfig",
    "MultiLayerConfiguration",
    "NeuralNetConfiguration",
    "MultiLayerNetwork",
]
