"""HTTP front end for the inference engine (DESIGN.md §13).

Stdlib ``ThreadingHTTPServer`` in the PR-1 ``StatusServer`` idiom (inner
handler class over the outer server's state, ``port=0`` auto-assign,
silenced request logging) — serving shares the observability stack's
transport, not a new framework:

- ``POST /v1/generate``  — continuous-batching decode; body
  ``{"prompt": [ids], "max_new_tokens", "temperature", "seed", "eos_id",
  "deadline_ms", "tenant", "priority"}`` → ``{"tokens", "finish_reason", "latency_s",
  "ttft_s"}`` (``tenant`` is an opaque caller identity: it lands on the
  capture record raw and on metrics through the bounded label fold)
- ``POST /v1/score``     — batched forward; ``{"inputs": [[...], ...]}``
  → ``{"outputs": [[...], ...]}``
- ``POST /v1/reload``    — hot swap to ``latest_valid_step()`` (or an
  explicit ``{"step": N}`` — the online loop's rollback path)
- ``POST /v1/migrate``   — disagg KV-page import (DESIGN.md §27):
  ``{"probe": {"prompt": [ids]}}`` → ``{"cached_len", "page_size"}``
  (plan the export: resident positions need no bytes); a full payload
  (``KVMigrator.export_payload``) installs the pages and blocks until
  decode completes, answering like ``/v1/generate``.  A payload whose
  probed prefix was evicted → 409 (re-export with full bytes)
- ``GET  /healthz``      — liveness + engine slot/queue stats, plus
  top-level ``role``/``warmed`` (the §27 probe contract: a prefill-role
  replica is verifiably not a decode target over HTTP)
- ``GET  /metrics``      — JSON registry snapshot
- ``GET  /metrics.prom`` — Prometheus text exposition (scrape target)

Error contract: backpressure rejections keep their HTTP status
(:class:`~.batcher.QueueFull` → 429, :class:`~.batcher.DeadlineExceeded`
→ 504), malformed requests → 400, reload with nothing to load → 409,
injected transients → 503 — load shedding is part of the API, not an
exception trace.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ..observability import METRICS, MetricsRegistry, trace
from ..resilience.faults import InjectedFault
from .batcher import ServingRejected


class ModelServer:
    """REST endpoint over an :class:`~.engine.InferenceEngine` and/or a
    :class:`~.engine.BatchScorer` (either may be None; its route 400s)."""

    def __init__(self, engine=None, scorer=None,
                 registry: MetricsRegistry = METRICS,
                 host: str = "127.0.0.1", port: int = 0,
                 request_timeout_s: float = 60.0, capture=None):
        self.engine = engine
        self.scorer = scorer
        self.registry = registry
        self.request_timeout_s = request_timeout_s
        # online-learning tap (DESIGN.md §23): a CaptureStore (or any
        # object with .append(dict)) receiving every completed
        # generation — prompt, tokens, optional caller feedback, and the
        # weight generation the response decoded under
        self.capture = capture
        self._migrator = None   # lazy KVMigrator for /v1/migrate imports
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, body: bytes,
                      content_type: str = "application/json") -> None:
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _json(self, code: int, payload) -> None:
                self._send(code, json.dumps(payload).encode())

            def do_GET(self):
                if self.path == "/healthz":
                    self._json(200, outer._health())
                elif self.path == "/metrics":
                    self._json(200, outer.registry.snapshot())
                elif self.path == "/metrics.prom":
                    self._send(200, outer.registry.to_prometheus().encode(),
                               "text/plain; version=0.0.4; charset=utf-8")
                else:
                    self._json(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                try:
                    length = int(self.headers.get("Content-Length") or 0)
                    payload = json.loads(self.rfile.read(length) or b"{}")
                    if not isinstance(payload, dict):
                        raise ValueError("body must be a JSON object")
                except (ValueError, json.JSONDecodeError) as e:
                    return self._json(400, {"error": f"bad request body: {e}"})
                # W3C trace propagation: a valid inbound traceparent binds
                # the ambient trace context for this handler thread, so
                # the engine's request spans join the caller's trace; a
                # malformed/absent header means the engine mints fresh
                ctx = trace.parse_traceparent(self.headers.get("traceparent"))
                try:
                    with trace.bind(*ctx) if ctx else trace.bind(None):
                        if self.path == "/v1/generate":
                            return self._json(200, outer._generate(payload))
                        if self.path == "/v1/score":
                            return self._json(200, outer._score(payload))
                        if self.path == "/v1/reload":
                            return self._json(200, outer._reload(payload))
                        if self.path == "/v1/migrate":
                            return self._json(200, outer._migrate(payload))
                    return self._json(404, {"error": f"no route {self.path}"})
                except ServingRejected as e:
                    # backpressure IS the API: 429 queue-full, 504 deadline
                    METRICS.increment("serving.http.rejected")
                    return self._json(e.status, {"error": str(e)})
                except InjectedFault as e:
                    return self._json(503, {"error": f"transient fault: {e}"})
                except TimeoutError as e:
                    return self._json(504, {"error": str(e)})
                except (TypeError, ValueError, KeyError) as e:
                    return self._json(400, {"error": str(e)})
                except (FileNotFoundError, RuntimeError) as e:
                    return self._json(409, {"error": str(e)})

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ handlers
    def _generate(self, p: dict) -> dict:
        if self.engine is None:
            raise ValueError("no InferenceEngine mounted on this server")
        if "prompt" not in p:
            raise ValueError("missing required field 'prompt'")
        eos = p.get("eos_id")
        dl = p.get("deadline_ms")
        tenant = str(p.get("tenant") or "")
        comp = self.engine.generate(
            p["prompt"], int(p.get("max_new_tokens", 16)),
            temperature=float(p.get("temperature", 0.0)),
            seed=int(p.get("seed", 0)),
            eos_id=int(eos) if eos is not None else None,
            deadline_ms=float(dl) if dl is not None else None,
            tenant=tenant,
            priority=int(p.get("priority", 0)),
            timeout=self.request_timeout_s)
        if self.capture is not None:
            # after completion only — rejected/expired requests never
            # reach the store, so replay sees exactly the served traffic.
            # The RAW tenant id rides the record (replay/fine-tune may
            # filter by tenant); the bounded fold applies to metric
            # names only.
            self.capture.append({
                "prompt": list(p["prompt"]), "tokens": comp.tokens,
                "finish_reason": comp.finish_reason,
                "feedback": p.get("feedback"),
                "tenant": tenant or None,
                "generation": comp.generation,
                "loaded_step": comp.loaded_step,
                "seed": int(p.get("seed", 0)),
                "temperature": float(p.get("temperature", 0.0))})
        return {"tokens": comp.tokens, "finish_reason": comp.finish_reason,
                "latency_s": comp.latency_s, "ttft_s": comp.ttft_s,
                "generation": comp.generation,
                "loaded_step": comp.loaded_step}

    def _score(self, p: dict) -> dict:
        if self.scorer is None:
            raise ValueError("no BatchScorer mounted on this server")
        if "inputs" not in p:
            raise ValueError("missing required field 'inputs'")
        xs = np.asarray(p["inputs"], np.float32)
        if xs.ndim < 2:
            raise ValueError("'inputs' must be a batch of rows")
        ys = self.scorer.score_batch(xs, timeout=self.request_timeout_s)
        return {"outputs": ys.tolist()}

    def _reload(self, p: dict | None = None) -> dict:
        if self.engine is None:
            raise ValueError("no InferenceEngine mounted on this server")
        step = (p or {}).get("step")
        return {"step": self.engine.reload(
            step=int(step) if step is not None else None)}

    def _migrate(self, p: dict) -> dict:
        """Disagg KV-page import (DESIGN.md §27).  Probe mode plans the
        export (how many positions are resident — those pages need no
        bytes on the wire); import mode installs the pages through the
        KVMigrator seam and blocks until decode completes, the wire
        twin of ``/v1/generate``."""
        if self.engine is None:
            raise ValueError("no InferenceEngine mounted on this server")
        # a DisaggScheduler fronts its decode engine; plain engines are
        # their own migration target
        target = getattr(self.engine, "decode", self.engine)
        if getattr(target, "page_pool", None) is None:
            raise ValueError("migration needs a paged engine "
                             "(the migration unit is a KV page)")
        probe = p.get("probe")
        if probe is not None:
            prompt = [int(t) for t in probe["prompt"]]
            if not prompt:
                raise ValueError("empty prompt")
            return {"cached_len": target.page_pool.peek_prefix(
                        prompt, len(prompt) - 1),
                    "page_size": target.page_pool.page_size}
        if "request" not in p:
            raise ValueError("missing required field 'request'")
        if self._migrator is None:
            from .disagg.migrate import KVMigrator
            self._migrator = KVMigrator(target)
        pending = self._migrator.import_payload(p)
        comp = pending.result(self.request_timeout_s)
        return {"tokens": comp.tokens, "finish_reason": comp.finish_reason,
                "latency_s": comp.latency_s, "ttft_s": comp.ttft_s,
                "generation": comp.generation,
                "loaded_step": comp.loaded_step}

    def _health(self) -> dict:
        out = {"ok": True}
        if self.engine is not None:
            stats = self.engine.stats()
            out["engine"] = stats
            # top-level twins of the two fields the §27 probe contract
            # depends on — verifiable over HTTP without knowing the
            # stats schema
            out["role"] = stats.get("role", "unified")
            out["warmed"] = bool(stats.get("warmed"))
        if self.scorer is not None:
            out["scorer"] = {"queue_depth": self.scorer._queue.depth()}
        return out

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "ModelServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._server.serve_forever, daemon=True,
                name="serving-http")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        self._server.server_close()

    def __enter__(self) -> "ModelServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
