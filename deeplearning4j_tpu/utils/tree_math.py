"""Pytree vector-space math used by the optimization engine.

The reference flattens params into one row vector and uses BLAS level-1 ops
(``MultiLayerNetwork.pack/params:744-788``, ``BaseOptimizer``).  Here the
natural representation is the pytree itself; these helpers give the same
axpy/dot/norm vocabulary over arbitrary param pytrees without materializing a
flat copy (XLA fuses the elementwise maps).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

tree_map = jax.tree_util.tree_map


def add(a, b):
    return tree_map(jnp.add, a, b)


def sub(a, b):
    return tree_map(jnp.subtract, a, b)


def scale(s, a):
    return tree_map(lambda x: s * x, a)


def axpy(s, a, b):
    """b + s*a."""
    return tree_map(lambda x, y: y + s * x, a, b)


def dot(a, b) -> jnp.ndarray:
    leaves = tree_map(lambda x, y: jnp.sum(x * y), a, b)
    return jax.tree_util.tree_reduce(jnp.add, leaves)


def norm2(a) -> jnp.ndarray:
    return jnp.sqrt(dot(a, a))


def neg(a):
    return tree_map(jnp.negative, a)


def zeros_like(a):
    return tree_map(jnp.zeros_like, a)


def max_abs(a) -> jnp.ndarray:
    leaves = tree_map(lambda x: jnp.max(jnp.abs(x)), a)
    return jax.tree_util.tree_reduce(jnp.maximum, leaves)


def clip_by_global_norm(a, max_norm: float):
    n = norm2(a)
    factor = jnp.minimum(1.0, max_norm / (n + 1e-12))
    return scale(factor, a)


def unit_norm(a):
    """Scale to unit L2 norm (``constrainGradientToUnitNorm``)."""
    n = norm2(a)
    return scale(1.0 / (n + 1e-12), a)
