"""Perf regression gate: diff a bench/smoke artifact against the committed
trajectory and refuse silent regressions.

The bench artifacts (``LAST_VALID_TPU_BENCH.json``, ``BENCH_r*.json``,
smoke JSON lines) record point-in-time numbers, but nothing *compared*
them — a 10% tokens/sec regression would land as just another artifact.
This gate closes the loop:

- ``BENCH_TRAJECTORY.json`` (committed at the repo root) holds the
  accepted history: one entry per recorded run, each a flat
  ``{series: value}`` dict plus provenance.
- ``python tools/perf_gate.py [artifact]`` extracts the key series from
  the artifact (tokens/sec, MFU, step time, TTFT p99, goodput fraction —
  whichever are present) and compares each against the NEWEST trajectory
  entry that has that series, direction-aware: higher-is-better series
  fail below ``base * (1 - tolerance)``, lower-is-better above
  ``base * (1 + tolerance)``.  A failure names the series, both values,
  and the tolerance — no silent drift.
- Entries are **device-scoped**: a CPU-fallback bench (``CPU_FALLBACK``
  metric suffix / ``TFRT_CPU`` device) is never held to a TPU baseline
  or vice versa.  Entries without a ``device`` tag match any artifact
  (legacy), and an entry may carry its own ``tolerance`` — a shared-core
  CPU baseline records a looser band than a quiet TPU one.
- ``--record`` appends the artifact's series as a new trajectory entry
  (after the gate passes; ``--force`` records anyway, for an accepted
  regression with a reason).

Running the gate twice on the same artifact is idempotent: equal values
are within any tolerance.  An empty trajectory seeds itself from the
first gated artifact (that run passes by definition and writes the
baseline the next run is held to).

Series are looked up through dotted paths with fallbacks, so the one gate
reads bench artifacts (``value``/``extra.mfu``/``extra.step_ms.median``),
chaos smoke results (``goodput.fraction``) and serving smoke results
(``ttft_s.p99``) without format negotiation.
"""

from __future__ import annotations

import json
import pathlib
import sys

_REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent

DEFAULT_TRAJECTORY = _REPO_ROOT / "BENCH_TRAJECTORY.json"
DEFAULT_ARTIFACT = _REPO_ROOT / "LAST_VALID_TPU_BENCH.json"
DEFAULT_TOLERANCE = 0.05

# (series, candidate dotted paths tried in order, direction)
SERIES: tuple[tuple[str, tuple[str, ...], str], ...] = (
    ("tokens_per_sec",
     ("value", "tokens_per_sec", "extra.e2e_with_transfers.tokens_per_sec"),
     "higher"),
    ("mfu", ("extra.mfu", "mfu"), "higher"),
    ("step_ms_median", ("extra.step_ms.median", "step_ms.median"), "lower"),
    ("resnet_images_per_sec",
     ("extra.resnet.images_per_sec_per_chip",), "higher"),
    ("ttft_p99_s", ("ttft_s.p99", "serving.ttft.p99", "ttft_p99_s"), "lower"),
    ("goodput_fraction",
     ("goodput.fraction", "goodput_fraction"), "higher"),
    ("fleet_scrape_ms", ("fleet.scrape_ms",), "lower"),
    ("replica_hours_saved_frac", ("autoscale.saved_frac",), "higher"),
    ("disagg_dedup_frac", ("disagg.dedup_frac",), "higher"),
)

DIRECTIONS = {name: direction for name, _, direction in SERIES}


def _dig(obj, path: str):
    for part in path.split("."):
        if not isinstance(obj, dict) or part not in obj:
            return None
        obj = obj[part]
    return obj


def extract(artifact: dict) -> dict[str, float]:
    """Pull every known series present in the artifact (dotted-path
    fallbacks; non-numeric hits are skipped, absences are not errors —
    a serving artifact has no MFU and that is fine)."""
    out: dict[str, float] = {}
    for name, paths, _direction in SERIES:
        for path in paths:
            v = _dig(artifact, path)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[name] = float(v)
                break
    return out


def extract_device(artifact: dict) -> str:
    """Device class of the artifact: "cpu" or "tpu".  Bench lines carry
    the compile device in ``extra.device`` and mark host fallbacks with a
    ``_CPU_FALLBACK`` metric suffix; smoke artifacts carry neither and
    are CPU runs by construction (tier-1 is a CPU mesh)."""
    metric = str(artifact.get("metric", ""))
    device = str(_dig(artifact, "extra.device") or artifact.get("device", ""))
    if "CPU_FALLBACK" in metric or device.upper().startswith(("TFRT_CPU",
                                                              "CPU")):
        return "cpu"
    if device or "tokens_per_sec" in metric:
        return "tpu"
    return "cpu"


def load_trajectory(path: pathlib.Path) -> dict:
    if path.exists():
        with open(path) as f:
            traj = json.load(f)
        traj.setdefault("entries", [])
        traj.setdefault("tolerance", DEFAULT_TOLERANCE)
        traj.setdefault("series_tolerance", {})
        return traj
    return {"tolerance": DEFAULT_TOLERANCE, "series_tolerance": {},
            "entries": []}


def _baseline_for(traj: dict, series: str,
                  device: str) -> tuple[float, dict] | None:
    """Newest same-device trajectory entry carrying this series (entries
    are appended, so scan from the end; entries without a ``device`` tag
    match any artifact)."""
    for entry in reversed(traj["entries"]):
        if entry.get("device", device) != device:
            continue
        v = entry.get("series", {}).get(series)
        if isinstance(v, (int, float)):
            return float(v), entry
    return None


def gate(current: dict[str, float], traj: dict,
         device: str = "cpu") -> tuple[list[str], list[str]]:
    """Compare extracted series against the trajectory.  Returns
    (failures, compared) — failure strings name series, values, and the
    tolerance that was exceeded."""
    failures: list[str] = []
    compared: list[str] = []
    for name, value in sorted(current.items()):
        hit = _baseline_for(traj, name, device)
        if hit is None:
            continue
        base, entry = hit
        tol = float(entry.get("tolerance")
                    or traj["series_tolerance"].get(name, traj["tolerance"]))
        compared.append(name)
        if DIRECTIONS[name] == "higher":
            floor = base * (1.0 - tol)
            if value < floor:
                failures.append(
                    f"{name}: {value:.6g} regressed below baseline "
                    f"{base:.6g} - {tol:.0%} tolerance (floor {floor:.6g})")
        else:
            ceil = base * (1.0 + tol)
            if value > ceil:
                failures.append(
                    f"{name}: {value:.6g} regressed above baseline "
                    f"{base:.6g} + {tol:.0%} tolerance (ceiling {ceil:.6g})")
    return failures, compared


def record(traj: dict, series: dict[str, float], *, label: str,
           source: str, device: str = "cpu",
           tolerance: float | None = None) -> None:
    entry = {
        "label": label,
        "source": source,
        "device": device,
        "series": {k: v for k, v in sorted(series.items())},
    }
    if tolerance is not None:
        entry["tolerance"] = tolerance
    traj["entries"].append(entry)


def run(artifact_path: pathlib.Path, trajectory_path: pathlib.Path,
        *, do_record: bool = False, force: bool = False,
        label: str = "") -> dict:
    with open(artifact_path) as f:
        artifact = json.load(f)
    current = extract(artifact)
    if not current:
        raise SystemExit(
            f"perf_gate: no known series in {artifact_path} "
            f"(looked for {', '.join(n for n, _, _ in SERIES)})")
    device = extract_device(artifact)
    traj = load_trajectory(trajectory_path)
    seeded = not traj["entries"]
    failures, compared = gate(current, traj, device)
    if seeded or (do_record and (not failures or force)):
        record(traj, current, label=label or artifact_path.name,
               source=str(artifact_path.name), device=device)
        with open(trajectory_path, "w") as f:
            json.dump(traj, f, indent=2)
            f.write("\n")
    return {
        "artifact": str(artifact_path),
        "trajectory": str(trajectory_path),
        "series": current,
        "device": device,
        "compared": compared,
        "seeded": seeded,
        "recorded": seeded or (do_record and (not failures or force)),
        "tolerance": traj["tolerance"],
        "failures": failures,
        "ok": not failures,
    }


def main(argv: list[str]) -> int:
    positional: list[str] = []
    trajectory, label = DEFAULT_TRAJECTORY, ""
    do_record = force = False
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--trajectory":
            trajectory = pathlib.Path(argv[i + 1])
            i += 2
        elif a == "--label":
            label = argv[i + 1]
            i += 2
        elif a == "--record":
            do_record = True
            i += 1
        elif a == "--force":
            force = True
            i += 1
        else:
            positional.append(a)
            i += 1
    artifact = pathlib.Path(positional[0]) if positional else DEFAULT_ARTIFACT
    result = run(artifact, trajectory,
                 do_record=do_record, force=force, label=label)
    print(json.dumps(result, indent=2))
    if result["failures"]:
        for fail in result["failures"]:
            print(f"perf_gate: FAIL {fail}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
