"""SLO-driven control plane (DESIGN.md §26).

The fleet measures everything it needs to act — burn rates, queue
depth, forecasts — and this package closes the loop: capacity follows
the SLO (:mod:`.autoscaler`) and overload degrades quality before it
degrades availability (:mod:`.overload`).  Control NEVER reaches into
serving internals: every action goes through the seams serving already
exposes (``PrefixRouter.scale_up``/``scale_down``, the pool's
quarantine-preserving drain, ``InferenceEngine.set_speculative``/
``set_max_new_cap``/``set_admission_hook``, the runner's
``register_worker``/``retire_worker``) — graftlint CT01 enforces that
no module in here mutates a hash ring directly.
"""

from .autoscaler import Autoscaler, AutoscalerConfig, ControlSignals
from .overload import (BrownoutConfig, BrownoutController, OverloadGate,
                       Throttled, TokenBucketAdmission)

__all__ = [
    "Autoscaler",
    "AutoscalerConfig",
    "ControlSignals",
    "BrownoutConfig",
    "BrownoutController",
    "OverloadGate",
    "Throttled",
    "TokenBucketAdmission",
]
