"""Tier-1 coverage of tools/kernel_smoke.py and the kernel tier's lint
hygiene: the microbench must run every registered candidate and publish
per-kernel timing through the observability layer, and ops/pallas must be
graftlint-clean with ZERO baseline entries (the kernel tier is new code —
it gets no legacy-debt ledger)."""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from deeplearning4j_tpu.analysis import Analyzer, Baseline, active  # noqa: E402
from deeplearning4j_tpu.observability import METRICS  # noqa: E402
from tools import kernel_smoke  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "graftlint.baseline.json")
PALLAS = os.path.join(REPO, "deeplearning4j_tpu", "ops", "pallas")


def test_kernel_smoke_runs_every_candidate_and_records_metrics():
    from deeplearning4j_tpu.ops.pallas import registry
    out = kernel_smoke.run()
    assert out["perf_claim"] is False
    expected = {f"{kind}.{c.name}" for kind in registry.kinds()
                for c in registry.candidates(kind)}
    assert set(out["kernels"]) == expected
    for rec in out["kernels"].values():
        assert rec["us_per_call"] > 0
        assert rec["bytes_moved_est"] > 0
    snap = METRICS.snapshot()
    for key in expected:
        assert f"kernel.{key}" in snap["timers"], key
        assert f"kernel.{key}.bytes_per_call" in snap["gauges"], key


def test_autopick_publishes_observability_gauges():
    from deeplearning4j_tpu.ops.pallas import registry
    registry.autopick("attention", [], incumbent="ring")
    snap = METRICS.snapshot()
    assert snap["gauges"]["bench.autopick.attention.candidates"] == 0
    assert snap["gauges"]["bench.autopick.attention.dropped"] == 2
    assert snap["gauges"]["bench.autopick.attention.adopted"] == 0.0
    assert snap["counters"]["bench.autopick.decisions"] == 1


def test_pallas_tier_is_lint_clean_with_zero_baseline_entries():
    analyzer = Analyzer(baseline=Baseline.load(BASELINE), root=REPO)
    findings = analyzer.analyze_paths([PALLAS])
    assert analyzer.errors == []
    fresh = active(findings)
    listing = "\n".join(
        f"  {f.path}:{f.line}: {f.rule} {f.message}" for f in fresh)
    assert not fresh, f"ops/pallas must stay lint-clean:\n{listing}"
    # no legacy-debt ledger for new code: the baseline must not mention
    # the kernel tier at all
    pallas_entries = [e for e in Baseline.load(BASELINE).entries
                     if "ops/pallas" in e.get("path", "")]
    assert pallas_entries == []
