"""Data layer + evaluation tests (mirror of the reference's iterator tests,
EvalTest, and the TestDataSetIterator fixture pattern)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import (
    CSVDataSetIterator,
    DataSet,
    DigitsDataSetIterator,
    IrisDataSetIterator,
    ListDataSetIterator,
    MnistDataSetIterator,
    MovingWindowDataSetIterator,
    MultipleEpochsIterator,
    ReconstructionDataSetIterator,
    SamplingDataSetIterator,
    TestDataSetIterator,
)
from deeplearning4j_tpu.datasets.dataset import to_outcome_matrix
from deeplearning4j_tpu.datasets.mnist_idx import (
    read_idx_images, read_idx_labels, write_idx_images, write_idx_labels,
)
from deeplearning4j_tpu.evaluation import ConfusionMatrix, Evaluation


def test_outcome_matrix():
    m = to_outcome_matrix([0, 2, 1], 3)
    np.testing.assert_array_equal(m, [[1, 0, 0], [0, 0, 1], [0, 1, 0]])


def test_dataset_pipeline_ops():
    ds = DataSet(np.arange(20, dtype=np.float32).reshape(10, 2),
                 to_outcome_matrix([0, 1] * 5, 2))
    sh = ds.shuffle(seed=0)
    assert sh.num_examples() == 10 and not np.array_equal(sh.features, ds.features)
    train, test = ds.split_test_and_train(7)
    assert train.num_examples() == 7 and test.num_examples() == 3
    norm = ds.normalize_zero_mean_unit_variance()
    np.testing.assert_allclose(norm.features.mean(axis=0), 0, atol=1e-5)
    filtered = ds.filter_by_outcome([1])
    assert filtered.num_examples() == 5
    batches = ds.batch_by(4)
    assert [b.num_examples() for b in batches] == [4, 4, 2]
    assert ds.sample(6, seed=1).num_examples() == 6


def test_iris_iterator():
    it = IrisDataSetIterator(batch=50)
    assert it.total_examples() == 150
    assert it.input_columns() == 4
    assert it.total_outcomes() == 3
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].features.shape == (50, 4)


def test_digits_iterator():
    it = DigitsDataSetIterator(batch=500)
    assert it.total_outcomes() == 10
    b = it.next()
    assert b.features.shape == (500, 64)
    assert b.features.max() <= 1.0


def test_mnist_fallback_shape():
    it = MnistDataSetIterator(batch=10)
    b = it.next()
    assert b.features.shape == (10, 784)
    assert set(np.unique(b.features)).issubset({0.0, 1.0})  # binarized


def test_mnist_idx_roundtrip(tmp_path):
    imgs = (np.random.default_rng(0).random((5, 28, 28)) * 255).astype(np.uint8)
    labels = np.array([1, 2, 3, 4, 5], dtype=np.uint8)
    write_idx_images(tmp_path / "img", imgs)
    write_idx_labels(tmp_path / "lbl", labels)
    np.testing.assert_array_equal(read_idx_images(tmp_path / "img"), imgs)
    np.testing.assert_array_equal(read_idx_labels(tmp_path / "lbl"), labels)


def test_csv_iterator(tmp_path):
    p = tmp_path / "data.csv"
    p.write_text("1.0,2.0,setosa\n3.0,4.0,virginica\n5.0,6.0,setosa\n")
    it = CSVDataSetIterator(batch=2, num_examples=3, path=p, label_col=2)
    b = it.next()
    assert b.features.shape == (2, 2)
    assert it.total_outcomes() == 2


def test_wrappers():
    ds = DataSet(np.random.default_rng(0).random((10, 4)).astype(np.float32),
                 to_outcome_matrix([0, 1] * 5, 2))
    inner = ListDataSetIterator(ds, batch=5)
    multi = MultipleEpochsIterator(3, inner)
    assert sum(b.num_examples() for b in multi) == 30
    samp = SamplingDataSetIterator(ds, batch=4, total_batches=5, seed=0)
    assert sum(b.num_examples() for b in samp) == 20
    recon = ReconstructionDataSetIterator(ListDataSetIterator(ds, batch=5))
    b = recon.next()
    np.testing.assert_array_equal(b.features, b.labels)
    tw = TestDataSetIterator(ds, batch=3)
    assert sum(b.num_examples() for b in tw) == 10


def test_moving_window_iterator():
    ds = DataSet(np.random.default_rng(0).random((2, 16)).astype(np.float32),
                 to_outcome_matrix([0, 1], 2))
    it = MovingWindowDataSetIterator(batch=4, data=ds, window_rows=2, window_cols=2)
    b = it.next()
    assert b.features.shape == (4, 4)
    assert it.total_examples() == 2 * 4  # 4 windows per 4x4 image


def test_preprocessor_hook():
    ds = DataSet(np.ones((4, 2), np.float32) * 10, to_outcome_matrix([0, 1, 0, 1], 2))
    it = ListDataSetIterator(ds, batch=2)
    it.set_pre_processor(lambda d: DataSet(d.features / 10.0, d.labels))
    assert it.next().features.max() == 1.0


def test_confusion_matrix():
    cm = ConfusionMatrix()
    cm.add("a", "a", 3)
    cm.add("a", "b", 1)
    cm.add("b", "b", 2)
    assert cm.count("a", "a") == 3
    assert cm.actual_total("a") == 4
    assert cm.predicted_total("b") == 3
    assert cm.total() == 6


def test_evaluation_metrics():
    ev = Evaluation()
    actual = to_outcome_matrix([0, 0, 1, 1, 2, 2], 3)
    guess = to_outcome_matrix([0, 1, 1, 1, 2, 0], 3)
    ev.eval(actual, guess)
    assert ev.accuracy() == pytest.approx(4 / 6)
    assert ev.precision(1) == pytest.approx(2 / 3)
    assert ev.recall(0) == pytest.approx(1 / 2)
    assert 0 < ev.f1() <= 1
    assert "Accuracy" in ev.stats()


def test_evaluation_perfect():
    ev = Evaluation()
    y = to_outcome_matrix([0, 1, 2], 3)
    ev.eval(y, y)
    assert ev.accuracy() == 1.0 and ev.f1() == 1.0


def test_evaluation_merge():
    y1 = to_outcome_matrix([0, 1], 2)
    ev1, ev2 = Evaluation(), Evaluation()
    ev1.eval(y1, y1)
    ev2.eval(y1, to_outcome_matrix([1, 1], 2))
    ev1.merge(ev2)
    assert ev1.accuracy() == pytest.approx(3 / 4)


def test_prefetch_to_device_order_and_placement():
    """prefetch_to_device must preserve order/count and yield device arrays
    (double-buffered host->device staging, SURVEY §7 L3)."""
    import jax

    from deeplearning4j_tpu.datasets.iterator import prefetch_to_device

    batches = [(np.full((2, 2), i), np.full((2,), i)) for i in range(5)]
    out = list(prefetch_to_device(batches, size=3))
    assert len(out) == 5
    for i, (a, b) in enumerate(out):
        assert isinstance(a, jax.Array) and float(a[0, 0]) == i
        assert float(b[0]) == i
    assert list(prefetch_to_device([], size=2)) == []
