"""One-off TPU tuning sweep: measure BERT/ResNet leg variants on the real
chip to pick bench.py's config (batch size, attention path).  Not part of
the benchmark contract — bench.py remains the single source of truth; this
script only informs which knobs bench.py should default to.

Usage: python tools/tune_tpu.py
           post|pallas|zero|kv|elastic|ablate|resnet_ablate|resnet_trace|
           bert|resnet|flash
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _median(ts):
    ts = sorted(ts)
    return ts[len(ts) // 2]


def bert_variant(batch, seq, attention, remat=False, iters=8):
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                       TransformerLM)
    from deeplearning4j_tpu.optimize import transforms as T

    cfg = TransformerConfig(vocab_size=32768, d_model=768, n_heads=12,
                            n_layers=12, d_ff=3072, max_len=seq,
                            causal=False, dtype=jnp.bfloat16, remat=remat,
                            attention=attention)
    model = TransformerLM(cfg)
    tx = T.adamw(T.warmup_cosine(1e-4, 10, 1000), weight_decay=0.01)
    params = model.init(jax.random.key(0))
    opt = model.init_opt(params, tx)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, (batch, seq), dtype=np.int32)
    a, b = jax.device_put(toks), jax.device_put(np.roll(toks, -1, 1))
    step = model.build_train_step(tx)
    params, opt, loss = step(params, opt, a, b)
    float(np.asarray(loss))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        params, opt, loss = step(params, opt, a, b)
        float(np.asarray(loss))
        times.append(time.perf_counter() - t0)
    med = _median(times)
    flops = cfg.flops_per_token() * batch * seq
    return {"batch": batch, "seq": seq, "attention": attention,
            "remat": remat, "median_ms": round(med * 1e3, 2),
            "tokens_per_sec": round(batch * seq / med, 1),
            "mfu": round(flops / (med * 197e12), 4)}


def resnet_variant(batch, iters=8, bn_fold=False):
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.models.resnet import (ResNetConfig, cross_entropy,
                                                  init_params)
    from deeplearning4j_tpu.optimize import transforms as T
    from deeplearning4j_tpu.optimize.transforms import apply_updates

    cfg = ResNetConfig.resnet50(bn_fold=bn_fold)
    tx = T.chain(T.momentum(0.9), T.sgd_lr(1e-2))

    def step(params, opt, images, labels):
        count, st = opt
        loss, g = jax.value_and_grad(cross_entropy)(params, images, labels, cfg)
        updates, st = tx.update(g, st, params, count)
        return apply_updates(params, updates), (count + 1, st), loss

    params = init_params(jax.random.key(0), cfg)
    opt = (jnp.zeros((), jnp.int32), tx.init(params))
    rng = np.random.default_rng(1)
    imgs = rng.standard_normal((batch, 224, 224, 3), dtype=np.float32)
    onehot = np.eye(cfg.num_classes, dtype=np.float32)[
        rng.integers(0, cfg.num_classes, batch)]
    a, b = jax.device_put(imgs), jax.device_put(onehot)
    jstep = jax.jit(step, donate_argnums=(0, 1))
    params, opt, loss = jstep(params, opt, a, b)
    float(np.asarray(loss))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        params, opt, loss = jstep(params, opt, a, b)
        float(np.asarray(loss))
        times.append(time.perf_counter() - t0)
    med = _median(times)
    flops = cfg.flops_per_image(224) * batch
    return {"batch": batch, "bn_fold": bn_fold,
            "median_ms": round(med * 1e3, 2),
            "images_per_sec": round(batch / med, 1),
            "mfu": round(flops / (med * 197e12), 4)}


def bert_ablate(batch=64, seq=512, iters=8):
    """Attribute step time: full train step vs fwd+bwd without optimizer vs
    encoder-only (no LM head) — the deltas localize optimizer and loss cost."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                       TransformerLM,
                                                       encode_local,
                                                       lm_loss_local)
    from deeplearning4j_tpu.optimize import transforms as T

    cfg = TransformerConfig(vocab_size=32768, d_model=768, n_heads=12,
                            n_layers=12, d_ff=3072, max_len=seq,
                            causal=False, dtype=jnp.bfloat16, remat=False)
    model = TransformerLM(cfg)
    tx = T.adamw(T.warmup_cosine(1e-4, 10, 1000), weight_decay=0.01)
    params = model.init(jax.random.key(0))
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, (batch, seq), dtype=np.int32)
    a = jax.device_put(toks)
    b = jax.device_put(np.roll(toks, -1, 1))

    def time_fn(fn, *args):
        r = fn(*args)
        jax.block_until_ready(r)
        ts = []
        for _ in range(iters):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            ts.append(time.perf_counter() - t0)
        return round(_median(ts) * 1e3, 2)

    out = {}
    opt = model.init_opt(params, tx)
    step = model.build_train_step(tx)
    r = step(params, opt, a, b)          # compile; donation -> rebuild below
    jax.block_until_ready(r)
    params2, opt2, _ = r
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        params2, opt2, loss = step(params2, opt2, a, b)
        float(np.asarray(loss))
        ts.append(time.perf_counter() - t0)
    out["full_step_ms"] = round(_median(ts) * 1e3, 2)

    grad_fn = jax.jit(jax.grad(lambda p: lm_loss_local(p, a, b, cfg)))
    out["grad_only_ms"] = time_fn(grad_fn, params2)
    loss_fn = jax.jit(lambda p: lm_loss_local(p, a, b, cfg))
    out["fwd_loss_ms"] = time_fn(loss_fn, params2)
    enc_fn = jax.jit(lambda p: encode_local(p, a, cfg).mean())
    out["fwd_encode_ms"] = time_fn(enc_fn, params2)
    return out


def resnet_ablate(batch=256, iters=6):
    """Localize ResNet's missing MFU (r4: 16.4% at batch 256): time the
    full step vs grad-only vs fwd-only, and the same fwd with BN reductions
    in bf16 instead of f32 — the VERDICT's named suspects."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.models import resnet as R
    from deeplearning4j_tpu.optimize import transforms as T
    from deeplearning4j_tpu.optimize.transforms import apply_updates

    cfg = R.ResNetConfig.resnet50()
    tx = T.chain(T.momentum(0.9), T.sgd_lr(1e-2))
    params = R.init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(1)
    imgs = rng.standard_normal((batch, 224, 224, 3), dtype=np.float32)
    onehot = np.eye(cfg.num_classes, dtype=np.float32)[
        rng.integers(0, cfg.num_classes, batch)]
    a, b = jax.device_put(imgs), jax.device_put(onehot)

    def time_fn(fn, *args):
        jax.block_until_ready(fn(*args))
        ts = []
        for _ in range(iters):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            ts.append(time.perf_counter() - t0)
        return round(_median(ts) * 1e3, 2)

    out = {"batch": batch}

    def step(params, opt, images, labels):
        count, st = opt
        loss, g = jax.value_and_grad(R.cross_entropy)(params, images, labels, cfg)
        updates, st = tx.update(g, st, params, count)
        return apply_updates(params, updates), (count + 1, st), loss

    opt = (jnp.zeros((), jnp.int32), tx.init(params))
    jstep = jax.jit(step)                          # no donation: params reused
    out["full_step_ms"] = time_fn(lambda: jstep(params, opt, a, b))
    out["grad_only_ms"] = time_fn(jax.jit(
        jax.grad(lambda p: R.cross_entropy(p, a, b, cfg))), params)
    out["fwd_only_ms"] = time_fn(jax.jit(
        lambda p: R.cross_entropy(p, a, b, cfg)), params)

    # the shippable bf16-apply path: bn_fold=True (stats stay f32, the
    # elementwise normalize becomes a folded per-channel bf16 affine)
    import dataclasses
    fcfg = dataclasses.replace(cfg, bn_fold=True)
    try:
        out["fwd_bnfold_ms"] = time_fn(jax.jit(
            lambda p: R.cross_entropy(p, a, b, fcfg)), params)
        out["grad_bnfold_ms"] = time_fn(jax.jit(
            jax.grad(lambda p: R.cross_entropy(p, a, b, fcfg))), params)
    except Exception as e:
        out["bnfold_error"] = repr(e)[:200]
    return out


def _xplane_top_ops(log_dir, n=12):
    """Sum device-plane event durations per op from the .xplane.pb trace —
    the top-N table VERDICT item 2 asks to commit."""
    from pathlib import Path

    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = sorted(Path(log_dir).rglob("*.xplane.pb"))
    if not paths:
        return {"error": f"no xplane.pb under {log_dir}"}
    xspace = xplane_pb2.XSpace()
    xspace.ParseFromString(paths[-1].read_bytes())
    device = [pl for pl in xspace.planes
              if "TPU" in pl.name or "/device" in pl.name.lower()]
    if not device:                 # CPU run: fall back to the host plane
        device = [pl for pl in xspace.planes if "/host:" in pl.name]
    totals = {}
    for plane in device:
        meta = {m_id: m.name for m_id, m in plane.event_metadata.items()}
        for line in plane.lines:
            for ev in line.events:
                name = meta.get(ev.metadata_id, str(ev.metadata_id))
                totals[name] = totals.get(name, 0) + ev.duration_ps
    top = sorted(totals.items(), key=lambda kv: -kv[1])[:n]
    total_ps = sum(totals.values()) or 1
    return {"plane_total_ms": round(total_ps / 1e9, 2),
            "top_ops": [{"op": k[:80], "ms": round(v / 1e9, 3),
                         "pct": round(100 * v / total_ps, 1)}
                        for k, v in top]}


def resnet_trace(batch=256, steps=3, log_dir="xplane_resnet"):
    """Capture an XPlane trace of the ResNet-50 train step and print the
    top-op table (parsed in-container via the TF xplane proto)."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.models.resnet import (ResNetConfig, cross_entropy,
                                                  init_params)
    from deeplearning4j_tpu.optimize import transforms as T
    from deeplearning4j_tpu.optimize.transforms import apply_updates
    from deeplearning4j_tpu.parallel.observe import profiler_trace

    cfg = ResNetConfig.resnet50()
    tx = T.chain(T.momentum(0.9), T.sgd_lr(1e-2))

    def step(params, opt, images, labels):
        count, st = opt
        loss, g = jax.value_and_grad(cross_entropy)(params, images, labels, cfg)
        updates, st = tx.update(g, st, params, count)
        return apply_updates(params, updates), (count + 1, st), loss

    params = init_params(jax.random.key(0), cfg)
    opt = (jnp.zeros((), jnp.int32), tx.init(params))
    rng = np.random.default_rng(1)
    imgs = rng.standard_normal((batch, 224, 224, 3), dtype=np.float32)
    onehot = np.eye(cfg.num_classes, dtype=np.float32)[
        rng.integers(0, cfg.num_classes, batch)]
    a, b = jax.device_put(imgs), jax.device_put(onehot)
    jstep = jax.jit(step, donate_argnums=(0, 1))
    params, opt, loss = jstep(params, opt, a, b)     # compile outside trace
    float(np.asarray(loss))
    with profiler_trace(log_dir):
        for _ in range(steps):
            params, opt, loss = jstep(params, opt, a, b)
            float(np.asarray(loss))
    try:
        return {"batch": batch, "steps": steps, "log_dir": log_dir,
                **_xplane_top_ops(log_dir)}
    except Exception as e:
        return {"batch": batch, "log_dir": log_dir,
                "parse_error": repr(e)[:300]}


def flash_check():
    """Correctness of the Pallas kernel vs the XLA ring path on-chip, then
    its speed inside the full model."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.models.transformer import ring_attention
    from deeplearning4j_tpu.ops.flash_attention import flash_attention

    B, T, H, D = 4, 512, 12, 64
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.bfloat16)
               for _ in range(3))
    res = {}
    for causal in (False, True):
        f = jax.jit(lambda q, k, v, c=causal: flash_attention(q, k, v, causal=c))
        r = jax.jit(lambda q, k, v, c=causal: ring_attention(
            q, k, v, n_sp=1, sp_axis=None, causal=c, t_local=T))
        err = float(np.max(np.abs(np.asarray(f(q, k, v), np.float32)
                                  - np.asarray(r(q, k, v), np.float32))))
        res[f"fwd_err_causal_{causal}"] = round(err, 5)

    def loss_f(q, k, v):
        return (flash_attention(q, k, v, causal=False).astype(jnp.float32) ** 2).mean()

    def loss_r(q, k, v):
        return (ring_attention(q, k, v, n_sp=1, sp_axis=None, causal=False,
                               t_local=T).astype(jnp.float32) ** 2).mean()

    gf = jax.jit(jax.grad(loss_f))(q, k, v)
    gr = jax.jit(jax.grad(loss_r))(q, k, v)
    res["grad_err"] = round(float(np.max(np.abs(
        np.asarray(gf, np.float32) - np.asarray(gr, np.float32)))), 5)
    return res


def _timed(fn, *args, iters=8):
    import jax
    jax.block_until_ready(fn(*args))            # compile outside the timing
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        times.append(time.perf_counter() - t0)
    return _median(times)


def pallas_battery(iters=8, shapes=None):
    """Generic TUNE rows for the ops/pallas kernel tier, one row per
    (kernel, candidate, block config) plus a correctness row per
    candidate — the schema ``bench.py``'s registry auto-pick consumes
    (``{"kernel", "candidate", "block", "tokens_per_sec"}`` /
    ``{"kernel", "candidate", "check"}``).  Yields dicts; the caller
    prints them as JSONL."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.ops.pallas import registry
    from deeplearning4j_tpu.ops.pallas.matmul_int8 import (quantize,
                                                           top1_agreement)

    rng = np.random.default_rng(0)
    # shapes override exists so a CPU smoke can exercise every code path
    # at toy sizes; the on-chip battery always runs the real ones
    B, T, H, D, N, K, V = shapes or (4, 512, 12, 64, 4096, 768, 32768)
    qkv = tuple(jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.bfloat16)
                for _ in range(3))
    x = jnp.asarray(rng.standard_normal((N, K)), jnp.bfloat16)
    r = jnp.asarray(rng.standard_normal((N, K)), jnp.bfloat16)
    scale = jnp.ones((K,), jnp.float32)
    bias = jnp.zeros((K,), jnp.float32)
    head = jnp.asarray(rng.standard_normal((K, V)) * 0.05, jnp.bfloat16)
    tgt = jnp.asarray(rng.integers(0, V, N), jnp.int32)
    qw = quantize(jnp.asarray(rng.standard_normal((K, V)) * 0.05))
    # paged decode read: one query position per row over T-token pages
    ps_pg = 16 if T >= 128 else 4
    npg = -(-T // ps_pg)
    n_phys = B * npg + 1
    pg_q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.bfloat16)
    pg_k, pg_v = (jnp.asarray(rng.standard_normal((n_phys, ps_pg, H, D)),
                              jnp.bfloat16) for _ in range(2))
    pg_bt = jnp.asarray(rng.permutation(n_phys)[: B * npg].reshape(B, npg),
                        jnp.int32)
    pg_len = jnp.asarray(rng.integers(1, npg * ps_pg + 1, B), jnp.int32)

    def grad_err(fn, ref, *args):
        def loss(f):
            def l(*a):
                out = f(*a)
                if isinstance(out, tuple):
                    out = out[1]
                return (out.astype(jnp.float32) ** 2).mean()
            return l
        ga = jax.jit(jax.grad(loss(fn)))(*args)
        gb = jax.jit(jax.grad(loss(ref)))(*args)
        return float(np.max(np.abs(np.asarray(ga, np.float32)
                                   - np.asarray(gb, np.float32))))

    # (kind, tokens-per-call, call(fn, **block), check(cand))
    def attention_check(cand):
        o = cand.fn(*qkv)
        ref = cand.reference(*qkv)
        return {"max_err": float(np.max(np.abs(
                    np.asarray(o, np.float32) - np.asarray(ref, np.float32)))),
                "grad_err": grad_err(cand.fn, cand.reference, *qkv)}

    def ln_check(cand):
        _, h = cand.fn(x, r, scale, bias)
        _, hr = cand.reference(x, r, scale, bias)
        return {"max_err": float(np.max(np.abs(
            np.asarray(h, np.float32) - np.asarray(hr, np.float32))))}

    def xent_check(cand):
        a = float(cand.fn(x, head, tgt))
        b = float(cand.reference(x, head, tgt))
        return {"max_err": abs(a - b) / max(abs(b), 1e-9)}

    def paged_check(cand):
        o = cand.fn(pg_q, pg_k, pg_v, pg_bt, pg_len)
        ref = cand.reference(pg_q, pg_k, pg_v, pg_bt, pg_len)
        return {"max_err": float(np.max(np.abs(
            np.asarray(o, np.float32) - np.asarray(ref, np.float32))))}

    def int8_check(cand):
        o = cand.fn(x, qw)
        ref = cand.reference(x, qw)
        return {"max_err": float(np.max(np.abs(
                    np.asarray(o) - np.asarray(ref)))),
                "top1_agree": float(top1_agreement(o, ref))}

    suites = (
        ("attention", B * T, lambda fn, **blk: fn(*qkv, **blk),
         attention_check),
        ("layernorm_residual", N, lambda fn, **blk: fn(x, r, scale, bias,
                                                       **blk), ln_check),
        ("xent", N, lambda fn, **blk: fn(x, head, tgt, **blk), xent_check),
        ("int8_matmul", N, lambda fn, **blk: fn(x, qw, **blk), int8_check),
        ("paged_attention", B,
         lambda fn, **blk: fn(pg_q, pg_k, pg_v, pg_bt, pg_len, **blk),
         paged_check),
    )
    for kind, tokens, call, check in suites:
        for cand in registry.candidates(kind):
            try:
                yield {"kernel": kind, "candidate": cand.name,
                       "check": check(cand)}
            except Exception as e:
                yield {"kernel": kind, "candidate": cand.name,
                       "check_error": repr(e)[:300]}
            for blk in (cand.blocks or ({},)):
                try:
                    med = _timed(jax.jit(lambda *a, c=cand, b=dict(blk):
                                         call(c.fn, **b)), iters=iters)
                    yield {"kernel": kind, "candidate": cand.name,
                           "block": dict(blk), "median_ms": round(med * 1e3, 3),
                           "tokens_per_sec": round(tokens / med, 1)}
                except Exception as e:
                    yield {"kernel": kind, "candidate": cand.name,
                           "block": dict(blk), "error": repr(e)[:300]}


def kv_battery(iters=8, shapes=None):
    """KV-precision rows for the serving decode read (DESIGN.md §20):
    every ``paged_attention_int8`` candidate checked against the FLOAT
    pool's reference — ``max_err`` is the quantization band and
    ``top1_agree`` the adoption statistic the registry gate floors at
    0.999 — plus timing rows, a GQA (n_kv_heads < n_heads) geometry
    for each, and the per-page byte accounting behind the capacity
    table in ``tools/metrics_dump.py``.  Same JSONL schema as
    ``pallas_battery``."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.ops.pallas import registry
    from deeplearning4j_tpu.ops.pallas import kv_quant as kvq
    from deeplearning4j_tpu.ops.pallas.matmul_int8 import top1_agreement
    from deeplearning4j_tpu.ops.pallas.paged_attention import \
        reference_paged_attention

    rng = np.random.default_rng(0)
    B, H, D, ps, npg = shapes or (8, 16, 128, 16, 32)
    n_phys = B * npg + 1

    def geometry(kv_heads):
        q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
        kf, vf = (jnp.asarray(rng.standard_normal((n_phys, ps, kv_heads, D)),
                              jnp.float32) for _ in range(2))
        bt = jnp.asarray(rng.permutation(n_phys)[: B * npg].reshape(B, npg),
                         jnp.int32)
        ln = jnp.asarray(rng.integers(1, npg * ps + 1, B), jnp.int32)
        s0 = jnp.full((n_phys, kv_heads), kvq.neutral_scale(jnp.int8),
                      jnp.float32)
        kq, ks = kvq.requantize_pool(kf, s0, jnp.int8)
        vq, vs = kvq.requantize_pool(vf, s0, jnp.int8)
        return q, kf, vf, kq, vq, ks, vs, bt, ln

    for kv_heads in (H, H // 4):                 # MHA and 4-way GQA reads
        q, kf, vf, kq, vq, ks, vs, bt, ln = geometry(kv_heads)
        want = reference_paged_attention(q, kf, vf, bt, ln)
        for cand in registry.candidates("paged_attention_int8"):
            try:
                got = cand.fn(q, kq, vq, ks, vs, bt, ln)
                yield {"kernel": "paged_attention_int8",
                       "candidate": cand.name, "kv_heads": kv_heads,
                       "check": {
                           "max_err": float(np.max(np.abs(
                               np.asarray(got, np.float32)
                               - np.asarray(want, np.float32)))),
                           "top1_agree": float(top1_agreement(got, want))}}
            except Exception as e:
                yield {"kernel": "paged_attention_int8",
                       "candidate": cand.name, "kv_heads": kv_heads,
                       "check_error": repr(e)[:300]}
            try:
                med = _timed(jax.jit(lambda c=cand:
                                     c.fn(q, kq, vq, ks, vs, bt, ln)),
                             iters=iters)
                yield {"kernel": "paged_attention_int8",
                       "candidate": cand.name, "kv_heads": kv_heads,
                       "block": {}, "median_ms": round(med * 1e3, 3),
                       "tokens_per_sec": round(B / med, 1)}
            except Exception as e:
                yield {"kernel": "paged_attention_int8",
                       "candidate": cand.name, "kv_heads": kv_heads,
                       "block": {}, "error": repr(e)[:300]}
    # the capacity arithmetic the serving gauges report, per storage mode
    import dataclasses as _dc

    from deeplearning4j_tpu.models.transformer import TransformerConfig
    from deeplearning4j_tpu.serving.engine import kv_page_bytes
    mcfg = TransformerConfig(vocab_size=32768, d_model=H * D, n_heads=H,
                             n_layers=24, d_ff=4 * H * D, max_len=ps * npg)
    for kv_heads in (H, H // 4):
        cfg = _dc.replace(mcfg, n_kv_heads=kv_heads)
        fp = kv_page_bytes(cfg, ps, None)
        for mode in (None,) + kvq.KV_QUANT_MODES:
            yield {"battery": "kv_capacity", "kv_heads": kv_heads,
                   "kv_quant": mode, "page_bytes": kv_page_bytes(cfg, ps, mode),
                   "bytes_vs_float": round(kv_page_bytes(cfg, ps, mode) / fp, 4)}


def zero_battery(iters=12, d=4096, batch=64):
    """ZeRO rows: one per stage — step time plus the per-device
    params/opt-state bytes from the trainer's gauges.  On real chips this
    is the stage-selection table DESIGN.md §15 owes its numbers to; on
    CPU the byte columns are still exact (they come from shard metadata,
    not timing).  Yields JSONL row dicts like ``pallas_battery``."""
    import jax
    from deeplearning4j_tpu import observability
    from deeplearning4j_tpu.observability import METRICS
    from deeplearning4j_tpu.optimize import transforms as T
    from deeplearning4j_tpu.parallel import DataParallelTrainer

    observability.enable()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch, d)).astype(np.float32)
    y = rng.normal(size=(batch, 1)).astype(np.float32)

    def loss_fn(p, xb, yb, key=None):
        return ((xb @ p["w"] - yb) ** 2).mean()

    for stage in (0, 1, 2, 3):
        METRICS.reset()
        tr = DataParallelTrainer(loss_fn, T.adam(1e-3), zero_stage=stage)
        state = tr.init_state({"w": np.zeros((d, 1), np.float32)})
        state, lazy = tr.step(state, x, y)  # compile + settle placements
        lazy.block()
        tr._resolve_pending()
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            state, lazy = tr.step(state, x, y)
            lazy.block()
            times.append(time.perf_counter() - t0)
        tr._resolve_pending()
        g = METRICS.snapshot()["gauges"]

        def per_dev(prefix):
            vals = [v for k, v in g.items() if k.startswith(prefix)]
            return max(vals) if vals else None

        yield {"battery": "zero", "zero_stage": stage, "n_dp": tr.n_dp,
               "d": d, "batch": batch,
               "median_ms": round(_median(times) * 1e3, 3),
               "params_bytes_per_device": per_dev(
                   "train.params_bytes.device."),
               "opt_state_bytes_per_device": per_dev(
                   "train.opt_state_bytes.device.")}


def elastic_battery(iters=5, d=4096, steps=3):
    """Elasticity rows (ISSUE 13): reshard wall-clock per zero stage and
    (save_dp -> restore_dp) direction — save a checkpoint at one dp width,
    restore it at another through the resharding path, and time the
    restore.  On CPU the widths are virtual-device halves of the host
    mesh; on real chips this is the battery the owed ROADMAP-item-2
    hardware run measures resharding cost with (the number that prices a
    live shrink/grow against simply restarting).  Yields JSONL row dicts
    like ``zero_battery``."""
    import tempfile

    import jax
    from deeplearning4j_tpu import observability
    from deeplearning4j_tpu.observability import METRICS
    from deeplearning4j_tpu.optimize import transforms as T
    from deeplearning4j_tpu.parallel import (CheckpointManager,
                                             DataParallelTrainer, elastic_mesh)

    observability.enable()
    n_dev = len(jax.devices())
    if n_dev < 2:
        yield {"battery": "elastic", "skipped": f"{n_dev} device(s)"}
        return
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n_dev * 8, d)).astype(np.float32)
    y = rng.normal(size=(n_dev * 8, 1)).astype(np.float32)

    def loss_fn(p, xb, yb, key=None):
        return ((xb @ p["w"] - yb) ** 2).mean()

    def mk(width, stage):
        return DataParallelTrainer(
            loss_fn, T.adam(1e-3),
            mesh=elastic_mesh(jax.devices()[:width]), zero_stage=stage)

    params = {"w": np.zeros((d, 1), np.float32)}
    for stage in (0, 1, 2, 3):
        for save_dp, restore_dp in ((n_dev, n_dev // 2), (n_dev // 2, n_dev)):
            with tempfile.TemporaryDirectory() as ckpt_dir:
                mgr = CheckpointManager(ckpt_dir)
                src = mk(save_dp, stage)
                state = src.init_state(params)
                for _ in range(steps):
                    state, lazy = src.step(state, x, y)
                src.checkpoint(state, mgr)
                dst = mk(restore_dp, stage)
                tmpl = dst.init_state(params)
                times = []
                for _ in range(iters):
                    METRICS.reset()
                    t0 = time.perf_counter()
                    restored = dst.restore(tmpl, mgr)
                    jax.block_until_ready((restored.params, restored.tstate))
                    times.append(time.perf_counter() - t0)
                g = METRICS.snapshot()["gauges"]
                yield {"battery": "elastic", "zero_stage": stage,
                       "save_dp": save_dp, "restore_dp": restore_dp, "d": d,
                       "median_ms": round(_median(times) * 1e3, 3),
                       "reshard_seconds_gauge": g.get("elastic.reshard_seconds")}


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "bert"
    out = []
    if which == "elastic":
        # reshard cost battery: wall-clock to restore a checkpoint across
        # dp widths, per zero stage (the elastic tier's hardware row)
        for row in elastic_battery():
            print(json.dumps(row), flush=True)
        return
    if which == "zero":
        for row in zero_battery():
            print(json.dumps(row), flush=True)
        return
    if which == "kv":
        # KV-precision battery: quantized paged-read candidates vs the
        # float reference + page-byte capacity rows
        for row in kv_battery():
            print(json.dumps(row), flush=True)
        return
    if which == "pallas":
        # the kernel-tier battery alone: one generic row per (kernel,
        # candidate, block) + a check row per candidate, straight into
        # the registry auto-pick's schema
        for row in pallas_battery():
            print(json.dumps(row), flush=True)
        return
    if which == "post":
        # post-change battery: chunked-xent BERT (ring + flash) and the
        # space-to-depth ResNet at growing batch
        try:
            print(json.dumps({"flash_check": flash_check()}), flush=True)
        except Exception as e:
            print(json.dumps({"flash_check_error": repr(e)[:300]}), flush=True)
        try:
            for row in pallas_battery():
                print(json.dumps(row), flush=True)
        except Exception as e:
            print(json.dumps({"pallas_battery_error": repr(e)[:300]}),
                  flush=True)
        for fn, args, kw in ((bert_variant, (64, 512, "ring"), {}),
                             (bert_variant, (64, 512, "flash"), {}),
                             (bert_variant, (128, 512, "ring"), {}),
                             (bert_variant, (128, 512, "flash"), {}),
                             (resnet_variant, (256,), {}),
                             (resnet_variant, (256,), {"bn_fold": True}),
                             (resnet_variant, (512,), {}),
                             (resnet_variant, (512,), {"bn_fold": True})):
            try:
                print(json.dumps(fn(*args, **kw)), flush=True)
            except Exception as e:
                print(json.dumps({"args": str(args) + str(kw),
                                  "error": repr(e)[:300]}), flush=True)
        return
    if which == "ablate":
        print(json.dumps(bert_ablate()), flush=True)
        return
    if which == "resnet_ablate":
        try:
            print(json.dumps({"resnet_ablate": resnet_ablate()}), flush=True)
        except Exception as e:
            print(json.dumps({"resnet_ablate_error": repr(e)[:300]}), flush=True)
        return
    if which == "resnet_trace":
        try:
            print(json.dumps({"resnet_trace": resnet_trace()}), flush=True)
        except Exception as e:
            print(json.dumps({"resnet_trace_error": repr(e)[:300]}), flush=True)
        return
    if which == "bert":
        for batch in (64, 128, 256):
            try:
                out.append(bert_variant(batch, 512, "ring"))
            except Exception as e:
                out.append({"batch": batch, "error": repr(e)[:200]})
            print(json.dumps(out[-1]), flush=True)
    elif which == "flash":
        for batch in (64, 128):
            try:
                out.append(bert_variant(batch, 512, "flash"))
            except Exception as e:
                out.append({"batch": batch, "error": repr(e)[:200]})
            print(json.dumps(out[-1]), flush=True)
    elif which == "resnet":
        for batch in (128, 256):
            try:
                out.append(resnet_variant(batch))
            except Exception as e:
                out.append({"batch": batch, "error": repr(e)[:200]})
            print(json.dumps(out[-1]), flush=True)


if __name__ == "__main__":
    main()
