"""graftlint baseline: a committed ledger of accepted findings.

The baseline lets ``--check`` gate on NEW violations only: every entry is
a (rule, path, source-line-text) triple plus a human justification.  Line
numbers are deliberately not part of the match key — unrelated edits that
shift a file must not invalidate the ledger, while any edit to the
flagged line itself does (forcing a fresh look, which is the point of a
baseline over blanket suppression).
"""

from __future__ import annotations

import json
from typing import Iterable

from .core import Finding

VERSION = 1


class Baseline:
    """In-memory set of accepted findings, JSON-round-trippable."""

    def __init__(self, entries: list[dict] | None = None):
        self.entries = entries or []
        self._keys = {(e["rule"], e["path"], e["code"]) for e in self.entries}

    # ------------------------------------------------------------------ io
    @classmethod
    def load(cls, path: str) -> "Baseline":
        try:
            with open(path, encoding="utf-8") as fh:
                data = json.load(fh)
        except FileNotFoundError:
            return cls()
        if not isinstance(data, dict) or "entries" not in data:
            raise ValueError(f"{path}: not a graftlint baseline file")
        return cls(list(data["entries"]))

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as fh:
            json.dump({"version": VERSION, "entries": self.entries},
                      fh, indent=2, sort_keys=True)
            fh.write("\n")

    # ------------------------------------------------------------------ api
    def contains(self, finding: Finding) -> bool:
        return finding.key() in self._keys

    def stale_entries(self, findings: Iterable[Finding]) -> list[dict]:
        """Entries whose finding no longer occurs — candidates for removal
        (the hazard was fixed, or the line changed)."""
        seen = {f.key() for f in findings}
        return [e for e in self.entries
                if (e["rule"], e["path"], e["code"]) not in seen]

    @classmethod
    def from_findings(cls, findings: Iterable[Finding],
                      justification: str = "TODO: justify or fix") -> "Baseline":
        entries = [{"rule": f.rule, "path": f.path.replace("\\", "/"),
                    "line": f.line, "code": f.code,
                    "justification": justification}
                   for f in findings]
        # dedupe identical keys (same code line flagged twice)
        seen, unique = set(), []
        for e in entries:
            k = (e["rule"], e["path"], e["code"])
            if k not in seen:
                seen.add(k)
                unique.append(e)
        return cls(unique)
