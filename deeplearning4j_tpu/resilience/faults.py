"""Deterministic, seedable fault injection — the chaos half of the
resilience subsystem (DESIGN.md §12).

Every recovery path in this tree is testable in-process because the code
under test calls into ONE global :data:`FAULTS` injector at named sites;
when the injector is disarmed (the default) each site check is a single
attribute test, so production hot loops pay nothing.

Named sites (grep for ``FAULTS.maybe_fire`` / ``FAULTS.check``):

=====================  =====================================================
site                   effect when armed
=====================  =====================================================
``train.step``         :class:`TransientStepFault` raised before dispatching
                       a train step (``DataParallelTrainer._dispatch``)
``data.next``          :class:`DataIteratorFault` raised from the host batch
                       stream (``DataParallelTrainer._host_stream``)
``checkpoint.write``   checkpoint payload corrupted *after* checksums are
                       recorded (``kind``: ``truncate`` | ``bitflip``) — the
                       published checkpoint fails ``verify()``
``preempt``            simulated preemption: the supervisor's ``should_stop``
                       poll returns True (emergency checkpoint + resume)
``scaleout.worker``    :class:`WorkerKilled` raised in the worker loop — the
                       worker thread/process exits with its job still
                       assigned (heartbeats stop; eviction must recover)
``scaleout.worker.slow``  injected ``time.sleep(delay_s)`` before performing
                       a job (straggler simulation)
``scaleout.perform``   :class:`TransientStepFault` raised inside the job
                       execution path (prompt failure -> requeue/quarantine)
``serving.request``    :class:`TransientStepFault` raised at request
                       submission (``RequestQueue.submit``) — the HTTP
                       layer's 503 path
``serving.decode``     one decode-segment dispatch skipped
                       (``InferenceEngine``, via ``FAULTS.check``) — a
                       transient decode hiccup; engine state is untouched
                       and the next round retries, so completions stay
                       token-identical
``serving.page_pool``  paged-KV admission behaves as if the page pool were
                       exhausted (``InferenceEngine._admit``, via
                       ``FAULTS.check``) — the request is rejected with
                       :class:`serving.PagePoolExhausted` (HTTP 429) and
                       no page leaks; in-flight slots keep decoding
``serving.draft``      the speculative draft model's proposals are garbled
                       for one verify window (``InferenceEngine``, via
                       ``FAULTS.check``) — accept length degrades but
                       emitted tokens stay target-drawn and token parity
                       holds (the rejection-sampling safety argument)
``router.route``       :class:`TransientStepFault` raised before the router
                       picks a replica (``PrefixRouter.generate``) — the
                       router front end's 503 path, before any replica is
                       touched
``router.replica_down``  one replica behaves dead: its dispatches raise
                       ``ReplicaUnavailable`` and its health probes fail
                       (``PrefixRouter`` / ``ReplicaPool``, via
                       ``FAULTS.check``).  ``kind`` names the target
                       replica (default ``bitflip`` is treated as "any") —
                       the chaos plan for breaker quarantine + ring
                       re-admission
``mesh.shrink``        :class:`DeviceLossError` raised before dispatching a
                       train step (``DataParallelTrainer._dispatch``, via
                       ``FAULTS.check``) — ``kind`` is the number of chips
                       lost (default 1).  The supervisor rebuilds the mesh
                       from the survivors and reshards
``mesh.grow``          the supervisor's ``should_stop`` poll drains the run
                       (emergency checkpoint), then previously-lost devices
                       re-register and the mesh is rebuilt LARGER before
                       resuming (``TrainingSupervisor``, via
                       ``FAULTS.check``)
``checkpoint.reshard``  :class:`TransientStepFault` raised inside a
                       cross-width ``CheckpointManager.restore`` before any
                       leaf is re-split — a reshard that dies mid-flight is
                       retried by the supervisor like any step fault
``capture.write``      the capture store's active segment is damaged on
                       disk AFTER a record's fsync'd append
                       (``online/capture.py``, via ``FAULTS.check``;
                       ``kind``: ``truncate`` | ``bitflip``) — a torn tail
                       or bit-rot the checksummed replay must skip, never
                       propagate
``capture.replay``     :class:`CaptureReplayFault` raised at the start of a
                       capture-store replay (``CaptureStore.replay``) — a
                       transient read failure; the online loop abandons the
                       round and retries on the next one
``online.publish``     the online loop's publish step fails
                       (``OnlineLoop``): default kinds raise
                       :class:`TransientStepFault` (round aborted, retried
                       next round); ``kind="poison"`` instead rewrites the
                       just-published checkpoint's params WITH recomputed
                       checksums — a plausible-but-bad model that verifies
                       clean and must be caught by the canary gate
``online.reload``      :class:`TransientStepFault` raised before the online
                       loop hot-reloads a freshly published step into the
                       serving tier — the round aborts (serving stays on
                       its current generation) and retries next round
``online.rollback``    :class:`TransientStepFault` raised inside the online
                       loop's rollback path — rollback retries in place
                       until the injected budget (``max_fires``) exhausts;
                       a rollback is the recovery path and MUST complete
``control.autoscaler``  kills the autoscaler's control loop permanently
                       (``control/autoscaler.py``, via ``FAULTS.check``) —
                       the pool freezes at its current size (static
                       capacity), routing and drain state untouched;
                       ``control.autoscaler_alive`` drops to 0
``disagg.prefill_worker``  :class:`WorkerKilled` raised in a disagg
                       prefill worker (``serving/disagg/scheduler.py``) —
                       the worker thread dies with the request claimed;
                       the scheduler releases any prefill record, requeues
                       the request at the head of its tier, and respawns a
                       twin.  Decode state is never touched
``disagg.migrate``     :class:`TransientStepFault` raised inside
                       ``KVMigrator.migrate`` — before the decode-side
                       claim or mid-transfer with references held on both
                       sides; the unwind quarantines every claimed page and
                       the scheduler requeues (refcounts must balance to
                       zero leaked pages — the chaos-leg assertion)
=====================  =====================================================

Arming:

- context manager (tests)::

      with inject_faults(FaultSpec("train.step", at_step=5),
                         FaultSpec("checkpoint.write", kind="bitflip",
                                   at_step=2), seed=42):
          ...

- environment (subprocess workers, chaos CI):
  ``DL4J_TPU_FAULTS="train.step:at=5;checkpoint.write:kind=bitflip,p=0.5"``
  with ``DL4J_TPU_FAULTS_SEED=<int>``.  Parsed lazily on the first site
  check, so worker processes spawned with the variable inherit the plan.

Determinism: probability draws use a per-site ``random.Random`` seeded from
``(seed, site)`` and a per-site call counter — the same plan + seed fires
at the same call indices regardless of wall clock or interleaving of OTHER
sites.  Every fire increments ``faults.injected.<site>`` in the metrics
registry, so a chaos run's injected-fault schedule is visible next to the
recovery counters it should have triggered.
"""

from __future__ import annotations

import os
import random
import threading
from contextlib import contextmanager
from dataclasses import dataclass, field

from ..observability import METRICS

# --------------------------------------------------------------------------- errors

class InjectedFault(RuntimeError):
    """Base class for every exception the fault layer raises."""


class TransientStepFault(InjectedFault):
    """A single training step / job execution failed (retryable)."""


class DataIteratorFault(InjectedFault):
    """The input pipeline raised mid-stream (retryable)."""


class CaptureReplayFault(InjectedFault):
    """A capture-store replay failed mid-read (retryable next round)."""


class WorkerKilled(InjectedFault):
    """A scaleout worker died silently (no failure report — heartbeats
    just stop).  Raised inside the worker loop; never seen by the master."""


class PreemptionSignal(InjectedFault):
    """Simulated SIGTERM-style preemption notice."""


class DivergenceError(RuntimeError):
    """NaN/Inf loss detected at the async resolution point.

    ``step`` is the post-dispatch step number of the FIRST non-finite loss
    in the resolved window — the supervisor uses it to size the batch
    window to skip after rolling back.
    """

    def __init__(self, step: int, value: float):
        super().__init__(f"non-finite loss {value!r} at step {step}")
        self.step = step
        self.value = value


class DeviceLossError(RuntimeError):
    """One or more accelerator chips dropped out of the mesh mid-run.

    Injected by the ``mesh.shrink`` chaos site (on real hardware the
    analogue is an XLA runtime error naming a dead core).  Carries the
    step and the lost device objects so the supervisor can rebuild a mesh
    from the survivors and reshard onto it.
    """

    def __init__(self, step: int, devices):
        self.step = step
        self.devices = list(devices)
        names = [str(getattr(d, "id", d)) for d in self.devices]
        super().__init__(
            f"lost {len(self.devices)} device(s) [{', '.join(names)}] "
            f"at step {step}")


class TrainingPreempted(RuntimeError):
    """A real SIGTERM/SIGINT arrived: the emergency checkpoint was written
    and the supervisor is handing control back so the process can exit."""

    def __init__(self, step: int):
        super().__init__(f"preempted at step {step} (emergency checkpoint saved)")
        self.step = step


#: default exception per site for ``maybe_fire``
_SITE_EXC: dict[str, type[InjectedFault]] = {
    "train.step": TransientStepFault,
    "data.next": DataIteratorFault,
    "preempt": PreemptionSignal,
    "scaleout.worker": WorkerKilled,
    "scaleout.perform": TransientStepFault,
    "serving.request": TransientStepFault,
    "serving.decode": TransientStepFault,
    "router.route": TransientStepFault,
    "checkpoint.reshard": TransientStepFault,
    "capture.replay": CaptureReplayFault,
    "online.publish": TransientStepFault,
    "online.reload": TransientStepFault,
    "online.rollback": TransientStepFault,
    "control.autoscaler": TransientStepFault,
    # disagg tier (DESIGN.md §27): a killed prefill worker dies like a
    # scaleout worker (thread exits, twin respawns); a migrate fault is
    # transient — the scheduler requeues, refcounts must balance
    "disagg.prefill_worker": WorkerKilled,
    "disagg.migrate": TransientStepFault,
}


# --------------------------------------------------------------------------- specs

@dataclass
class FaultSpec:
    """One site's trigger: fire at an exact step/call index, or with a
    seeded per-call probability — never both silently (``at_step`` wins).

    ``max_fires`` bounds total fires (default 1: faults are *transient*
    by default, so a retried path does not re-fail forever); ``0`` means
    unbounded.  ``kind`` is a site-specific payload (checkpoint corruption
    flavor); ``delay_s`` is the injected sleep for slow-worker sites.
    """

    site: str
    probability: float = 0.0
    at_step: int | None = None
    kind: str = "bitflip"
    max_fires: int = 1
    delay_s: float = 0.05


@dataclass
class _SiteState:
    spec: FaultSpec
    calls: int = 0
    fires: int = 0
    rng: random.Random = field(default_factory=random.Random)


class FaultInjector:
    """The process-global chaos switchboard (see module docstring)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sites: dict[str, _SiteState] = {}
        self._armed = False
        self._env_checked = False

    # ------------------------------------------------------------- arming
    def arm(self, specs, seed: int = 0) -> None:
        with self._lock:
            self._sites = {}
            for spec in specs:
                st = _SiteState(spec=spec)
                st.rng.seed(f"{seed}:{spec.site}")
                self._sites[spec.site] = st
            self._armed = bool(self._sites)
            self._env_checked = True

    def disarm(self) -> None:
        with self._lock:
            self._sites = {}
            self._armed = False
            # re-allow env arming for the next explicit opt-in
            self._env_checked = False

    @property
    def armed(self) -> bool:
        return self._armed

    def _arm_from_env_locked(self) -> None:
        self._env_checked = True
        raw = os.environ.get("DL4J_TPU_FAULTS", "").strip()
        if not raw:
            return
        seed = int(os.environ.get("DL4J_TPU_FAULTS_SEED", "0"))
        specs = parse_fault_env(raw)
        for spec in specs:
            st = _SiteState(spec=spec)
            st.rng.seed(f"{seed}:{spec.site}")
            self._sites[spec.site] = st
        self._armed = bool(self._sites)

    # ------------------------------------------------------------- checks
    def check(self, site: str, step: int | None = None) -> FaultSpec | None:
        """Non-raising trigger test: returns the :class:`FaultSpec` when
        the site fires this call, else None.  The disarmed fast path is a
        single attribute test."""
        if not self._armed and self._env_checked:
            return None
        with self._lock:
            if not self._env_checked:
                self._arm_from_env_locked()
            st = self._sites.get(site)
            if st is None:
                return None
            st.calls += 1
            if st.spec.max_fires and st.fires >= st.spec.max_fires:
                return None
            if st.spec.at_step is not None:
                index = step if step is not None else st.calls
                fired = index == st.spec.at_step
            else:
                fired = st.rng.random() < st.spec.probability
            if not fired:
                return None
            st.fires += 1
        METRICS.increment(f"faults.injected.{site}")
        return st.spec

    def maybe_fire(self, site: str, step: int | None = None) -> None:
        """Raising trigger test: raises the site's mapped
        :class:`InjectedFault` subclass when the site fires."""
        spec = self.check(site, step)
        if spec is not None:
            exc = _SITE_EXC.get(site, InjectedFault)
            raise exc(f"injected fault at site {site!r}"
                      + (f" (step {step})" if step is not None else ""))

    def fire_count(self, site: str) -> int:
        with self._lock:
            st = self._sites.get(site)
            return st.fires if st is not None else 0

    def counts(self) -> dict[str, int]:
        with self._lock:
            return {s: st.fires for s, st in self._sites.items()}


def parse_fault_env(raw: str) -> list[FaultSpec]:
    """``"site:k=v,k=v;site2:k=v"`` -> specs.

    Keys: ``p``/``prob``/``probability``, ``at``/``at_step``, ``kind``,
    ``max``/``max_fires``, ``delay``/``delay_s``.  A site with no keys
    (``"preempt"``) fires once at probability 1.
    """
    specs = []
    for part in raw.split(";"):
        part = part.strip()
        if not part:
            continue
        site, _, kvs = part.partition(":")
        spec = FaultSpec(site=site.strip())
        if not kvs.strip():
            spec.probability = 1.0
        for kv in kvs.split(","):
            kv = kv.strip()
            if not kv:
                continue
            k, _, v = kv.partition("=")
            k, v = k.strip(), v.strip()
            if k in ("p", "prob", "probability"):
                spec.probability = float(v)
            elif k in ("at", "at_step"):
                spec.at_step = int(v)
            elif k == "kind":
                spec.kind = v
            elif k in ("max", "max_fires"):
                spec.max_fires = int(v)
            elif k in ("delay", "delay_s"):
                spec.delay_s = float(v)
            else:
                raise ValueError(f"unknown fault spec key {k!r} in {part!r}")
        specs.append(spec)
    return specs


#: the process-global injector every instrumented site consults
FAULTS = FaultInjector()


@contextmanager
def inject_faults(*specs: FaultSpec, seed: int = 0):
    """Arm :data:`FAULTS` with ``specs`` for the duration of the block."""
    FAULTS.arm(specs, seed=seed)
    try:
        yield FAULTS
    finally:
        FAULTS.disarm()


def corrupt_file(path, kind: str = "bitflip") -> None:
    """Damage a file in place — the checkpoint-corruption payloads.

    ``truncate`` keeps the first half (torn write); ``bitflip`` flips one
    byte in the middle (silent medium corruption).  Both must be caught by
    the checksum ``verify()`` pass, never by a lucky parse error.
    """
    data = path.read_bytes()
    if kind == "truncate":
        path.write_bytes(data[: max(1, len(data) // 2)])
    elif kind == "bitflip":
        mid = len(data) // 2
        flipped = bytes([data[mid] ^ 0xFF])
        path.write_bytes(data[:mid] + flipped + data[mid + 1:])
    else:
        raise ValueError(f"unknown corruption kind {kind!r}")
