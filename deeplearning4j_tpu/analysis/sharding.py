"""Sharding pass: mesh-axis bindings resolved per module, interprocedurally.

The SH rules need one fact the raw AST does not carry: *which mesh axes
are in scope* at a given expression.  Axis names enter a program in
exactly three ways in this codebase —

- a ``jax.sharding.Mesh(devices, axis_names)`` construction,
- the ``parallel/mesh.py`` helpers (``make_mesh``/``local_mesh``/
  ``elastic_mesh``/``shrink_mesh``/``grow_mesh``) that wrap it,
- a ``shard_map(fn, mesh=..., ...)`` / ``pmap(fn, axis_name=...)`` site
  that binds those axes over ``fn``'s body —

and this module threads them through all three: mesh-producing calls and
assignments are resolved to axis sets, wrap sites bind those sets onto
the wrapped function definitions (lambdas included), and bound axes
propagate one module-internal call level at a time to a fixed point, so
a helper invoked from a ``shard_map``-ed step inherits the step's axes.

Everything is deliberately *confidence-ranked*: a binding is either a
known ``frozenset`` of axis names, ``None`` ("wrapped, but through a
mesh we cannot resolve" — e.g. a mesh arriving as a parameter), or
absent ("never visibly wrapped").  SH01 only fires on KNOWN bindings;
unknown silences the rule rather than guessing.

The canonical axis-name registry is parsed straight out of
``parallel/mesh.py`` (the ``DP, TP, PP, SP, EP = ...`` constants and the
``AXES`` table) so the linter and the runtime can never disagree about
which axis names exist.  ``set_axis_registry`` is the test hook.
"""

from __future__ import annotations

import ast
import pathlib

from .core import dotted_name, last_segment
from .jitinfo import ModuleInfo

#: last-resort axis table, used only when parallel/mesh.py is unreadable
_FALLBACK_AXES = ("dp", "tp", "pp", "sp", "ep")

#: collective primitives (and this repo's same-named wrappers) that take
#: a mesh-axis name argument
COLLECTIVES = frozenset({
    "psum", "pmean", "pmax", "pmin", "all_gather", "ppermute", "pshuffle",
    "all_to_all", "axis_index", "psum_scatter",
})

#: collectives whose FIRST positional argument is the axis name
_AXIS_FIRST = frozenset({"axis_index"})

#: sentinel distinguishing "never wrapped" from "wrapped, axes unknown"
_UNWRAPPED = object()

_registry_cache: tuple[frozenset, dict] | None = None
_registry_override: tuple[frozenset, dict] | None = None


def set_axis_registry(axes) -> None:
    """Test hook: replace the parsed mesh.py axis table (None restores)."""
    global _registry_override
    if axes is None:
        _registry_override = None
    else:
        axes = tuple(axes)
        _registry_override = (frozenset(axes),
                              {a.upper(): a for a in axes})


def _parse_mesh_module() -> tuple[frozenset, dict]:
    """(axis-name set, constant-name -> axis-name) from parallel/mesh.py."""
    consts: dict[str, str] = {}
    axes: list[str] = []
    path = (pathlib.Path(__file__).resolve().parents[1]
            / "parallel" / "mesh.py")
    try:
        tree = ast.parse(path.read_text())
    except (OSError, SyntaxError):
        return frozenset(_FALLBACK_AXES), {a.upper(): a for a in _FALLBACK_AXES}
    for stmt in tree.body:
        targets = []
        value = None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        for target in targets:
            # DP, TP, PP, SP, EP = "dp", "tp", "pp", "sp", "ep"
            if isinstance(target, ast.Tuple) and isinstance(value, ast.Tuple) \
                    and len(target.elts) == len(value.elts):
                for t, v in zip(target.elts, value.elts):
                    if isinstance(t, ast.Name) and isinstance(v, ast.Constant) \
                            and isinstance(v.value, str):
                        consts[t.id] = v.value
            # AXES: tuple[str, ...] = (DP, TP, PP, SP, EP)
            elif isinstance(target, ast.Name) and target.id == "AXES" \
                    and isinstance(value, (ast.Tuple, ast.List)):
                for v in value.elts:
                    if isinstance(v, ast.Constant) and isinstance(v.value, str):
                        axes.append(v.value)
                    elif isinstance(v, ast.Name) and v.id in consts:
                        axes.append(consts[v.id])
    if not axes:
        axes = list(consts.values()) or list(_FALLBACK_AXES)
    return frozenset(axes), consts


def axis_registry() -> frozenset:
    """The canonical set of mesh-axis names (SH02's ground truth)."""
    return _registry_tables()[0]


def axis_constants() -> dict:
    """Constant name -> axis name (``DP`` -> ``"dp"``) from mesh.py."""
    return _registry_tables()[1]


def _registry_tables() -> tuple[frozenset, dict]:
    global _registry_cache
    if _registry_override is not None:
        return _registry_override
    if _registry_cache is None:
        _registry_cache = _parse_mesh_module()
    return _registry_cache


class ShardMapSite:
    """One ``shard_map(fn, ...)`` call with its resolved pieces."""

    __slots__ = ("call", "target", "mesh_axes", "in_specs", "out_specs")

    def __init__(self, call, target, mesh_axes, in_specs, out_specs):
        self.call = call            # the shard_map ast.Call
        self.target = target        # wrapped FunctionDef/Lambda, or None
        self.mesh_axes = mesh_axes  # frozenset | None
        self.in_specs = in_specs    # ast node or None
        self.out_specs = out_specs  # ast node or None


class ShardingInfo:
    """Per-module axis-binding facts, computed once and cached on the
    :class:`ModuleInfo` (see :func:`sharding_info`)."""

    def __init__(self, module: ModuleInfo):
        self.module = module
        #: local names (incl. dotted like ``self.mesh``) -> axis sets
        self.mesh_axes: dict[str, frozenset | None] = {}
        #: def/lambda -> frozenset (known axes) | None (wrapped, unknown)
        self.bound: dict[ast.AST, object] = {}
        self.shard_map_sites: list[ShardMapSite] = []
        #: every collective call node -> its enclosing def/lambda chain
        self.collective_chains: dict[ast.Call, tuple] = {}
        self._defs_by_name: dict[str, list] = {}
        for fn in ast.walk(module.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._defs_by_name.setdefault(fn.name, []).append(fn)
        self._collect_mesh_vars()
        self._collect_bindings()
        self._propagate()
        self._collect_collectives()

    # ------------------------------------------------------------- axes
    def resolve_axis(self, node) -> str | None:
        """Literal/constant-resolved axis name, else None."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        name = self.module.canonical(node) or dotted_name(node)
        if not name:
            return None
        base = last_segment(name)
        consts = axis_constants()
        if base in consts and (name == base or name.endswith(f"mesh.{base}")):
            return consts[base]
        return None

    def resolve_axis_tuple(self, node) -> tuple | None:
        """All-resolvable tuple/list of axis names, else None."""
        if isinstance(node, (ast.Tuple, ast.List)):
            out = []
            for elt in node.elts:
                axis = self.resolve_axis(elt)
                if axis is None:
                    return None
                out.append(axis)
            return tuple(out)
        axis = self.resolve_axis(node)
        return None if axis is None else (axis,)

    def spec_signature(self, node):
        """Canonical signature of a literal sharding expression —
        ``NamedSharding(mesh, P('dp'))`` / ``P('dp', None)`` become
        ``('dp',)`` / ``('dp', None)`` (tuple entries for multi-axis
        dims), ``replicated(mesh)`` / ``P()`` become ``()``.  None when
        the expression is not statically resolvable (a variable, a
        helper call with runtime axes)."""
        if not isinstance(node, ast.Call):
            return None
        canon = self.module.canonical(node.func) or ""
        base = last_segment(canon)
        if base == "NamedSharding":
            spec = node.args[1] if len(node.args) > 1 else None
            for kw in node.keywords:
                if kw.arg == "spec":
                    spec = kw.value
            return None if spec is None else self.spec_signature(spec)
        if base == "replicated":
            return ()
        if base == "PartitionSpec":
            out = []
            for arg in node.args:
                if isinstance(arg, ast.Constant) and arg.value is None:
                    out.append(None)
                    continue
                axes = self.resolve_axis_tuple(arg)
                if axes is None:
                    return None
                if isinstance(arg, (ast.Tuple, ast.List)):
                    out.append(axes)
                else:
                    out.append(axes[0])
            return tuple(out)
        return None

    # -------------------------------------------------------- mesh vars
    def _axes_from_mesh_call(self, call: ast.Call):
        """frozenset | None (unknown) | _UNWRAPPED (not a mesh call)."""
        canon = self.module.canonical(call.func) or ""
        base = last_segment(canon)
        if base == "Mesh":
            axis_arg = call.args[1] if len(call.args) > 1 else None
            for kw in call.keywords:
                if kw.arg == "axis_names":
                    axis_arg = kw.value
            if axis_arg is None:
                return None
            axes = self.resolve_axis_tuple(axis_arg)
            return None if axes is None else frozenset(axes)
        if base == "make_mesh":
            return frozenset(axis_registry())
        if base in ("local_mesh", "elastic_mesh"):
            axis_arg = None
            for kw in call.keywords:
                if kw.arg == "axis":
                    axis_arg = kw.value
            if axis_arg is None and len(call.args) > 1:
                axis_arg = call.args[1]
            if axis_arg is None:
                return frozenset({axis_constants().get("DP", "dp")})
            axis = self.resolve_axis(axis_arg)
            return None if axis is None else frozenset({axis})
        if base in ("shrink_mesh", "grow_mesh"):
            # dp-only by contract (see parallel/mesh.py)
            return frozenset({axis_constants().get("DP", "dp")})
        return _UNWRAPPED

    def _collect_mesh_vars(self) -> None:
        for node in ast.walk(self.module.tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            axes = self._axes_from_mesh_call(node.value)
            if axes is _UNWRAPPED:
                continue
            for target in node.targets:
                name = dotted_name(target)
                if name is not None:
                    # two assigns with different axes -> unknown
                    prior = self.mesh_axes.get(name, axes)
                    self.mesh_axes[name] = axes if prior == axes else None

    def _mesh_arg_axes(self, node):
        """Axis set of a ``mesh=`` argument expression (frozenset|None)."""
        if isinstance(node, ast.Call):
            axes = self._axes_from_mesh_call(node)
            return None if axes is _UNWRAPPED else axes
        name = dotted_name(node)
        if name is not None and name in self.mesh_axes:
            return self.mesh_axes[name]
        return None

    # --------------------------------------------------------- bindings
    def _def_for_name(self, basename: str, lineno: int):
        """The local def ``basename`` refers to near ``lineno``.  With
        several same-named defs (nested-builder ``local`` idiom), the
        closest one defined ABOVE the reference wins — the reference
        pattern is ``def local(...)`` followed by ``shard_map(local)``
        a few lines later in the same builder."""
        cands = self._defs_by_name.get(basename)
        if not cands:
            return None
        if len(cands) == 1:
            return cands[0]
        above = [d for d in cands if d.lineno <= lineno]
        return max(above, key=lambda d: d.lineno) if above else None

    def _wrap_target(self, expr, lineno: int):
        """The def/lambda a wrap site's first argument refers to."""
        if isinstance(expr, ast.Lambda):
            return expr
        if isinstance(expr, ast.Call) and expr.args:
            # shard_map(jax.checkpoint(step), ...) style nesting
            return self._wrap_target(expr.args[0], lineno)
        name = dotted_name(expr)
        if name is not None:
            return self._def_for_name(last_segment(name), lineno)
        return None

    def _bind(self, target, axes) -> None:
        if target is None:
            return
        prior = self.bound.get(target, _UNWRAPPED)
        if axes is None or prior is None:
            self.bound[target] = None       # unknown dominates
        elif prior is _UNWRAPPED:
            self.bound[target] = frozenset(axes)
        else:
            self.bound[target] = prior | frozenset(axes)

    def _collect_bindings(self) -> None:
        for node in ast.walk(self.module.tree):
            if not isinstance(node, ast.Call):
                continue
            canon = self.module.canonical(node.func) or ""
            base = last_segment(canon)
            if base == "shard_map":
                target = (self._wrap_target(node.args[0], node.lineno)
                          if node.args else None)
                mesh_arg = node.args[1] if len(node.args) > 1 else None
                in_specs = node.args[2] if len(node.args) > 2 else None
                out_specs = node.args[3] if len(node.args) > 3 else None
                for kw in node.keywords:
                    if kw.arg == "mesh":
                        mesh_arg = kw.value
                    elif kw.arg == "in_specs":
                        in_specs = kw.value
                    elif kw.arg == "out_specs":
                        out_specs = kw.value
                axes = (self._mesh_arg_axes(mesh_arg)
                        if mesh_arg is not None else None)
                self._bind(target, axes)
                self.shard_map_sites.append(
                    ShardMapSite(node, target, axes, in_specs, out_specs))
            elif base == "pmap" or canon.endswith(".pmap"):
                target = (self._wrap_target(node.args[0], node.lineno)
                          if node.args else None)
                axis_arg = None
                for kw in node.keywords:
                    if kw.arg == "axis_name":
                        axis_arg = kw.value
                if axis_arg is None:
                    self._bind(target, None)    # unnamed axis: unknown
                else:
                    axis = self.resolve_axis(axis_arg)
                    self._bind(target,
                               None if axis is None else frozenset({axis}))

    def _propagate(self) -> None:
        """Bound axes flow to module-local defs called from bound defs —
        the interprocedural half, run to a (bounded) fixed point."""
        for _ in range(len(self._defs_by_name) + 1):
            changed = False
            for fn, axes in list(self.bound.items()):
                if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef,
                                       ast.Lambda)):
                    continue
                for call in ast.walk(fn):
                    if not isinstance(call, ast.Call):
                        continue
                    callee = dotted_name(call.func)
                    if callee is None:
                        continue
                    child = self._def_for_name(last_segment(callee),
                                               call.lineno)
                    if child is None or child is fn:
                        continue
                    prior = self.bound.get(child, _UNWRAPPED)
                    if axes is None:
                        if prior is not None:
                            self.bound[child] = None
                            changed = True
                    elif prior is _UNWRAPPED:
                        self.bound[child] = frozenset(axes)
                        changed = True
                    elif prior is not None and not (axes <= prior):
                        self.bound[child] = prior | axes
                        changed = True
            if not changed:
                return

    # ------------------------------------------------------ collectives
    def collective_axis_arg(self, call: ast.Call):
        """The axis-name argument expression of a collective call, or
        None when ``call`` is not a collective / has no axis argument."""
        canon = self.module.canonical(call.func) or ""
        base = last_segment(canon)
        if base not in COLLECTIVES:
            return None
        for kw in call.keywords:
            if kw.arg in ("axis_name", "axis"):
                return kw.value
        idx = 0 if base in _AXIS_FIRST else 1
        if idx < len(call.args):
            return call.args[idx]
        return None

    def _collect_collectives(self) -> None:
        def walk(node, chain):
            for child in ast.iter_child_nodes(node):
                sub = chain
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    sub = chain + (child,)
                if isinstance(child, ast.Call):
                    canon = self.module.canonical(child.func) or ""
                    if last_segment(canon) in COLLECTIVES:
                        self.collective_chains[child] = chain
                walk(child, sub)

        walk(self.module.tree, ())

    def axes_for_chain(self, chain) -> frozenset | None:
        """Known bound axes over a lexical def chain; None = unknown
        (an unresolvable wrap in the chain, or nothing wrapped at all)."""
        known: set = set()
        any_known = False
        for fn in chain:
            b = self.bound.get(fn, _UNWRAPPED)
            if b is None:
                return None
            if b is not _UNWRAPPED:
                known |= b
                any_known = True
        return frozenset(known) if any_known else None


def sharding_info(module: ModuleInfo) -> ShardingInfo:
    """The module's (cached) sharding pass result."""
    info = getattr(module, "_sharding_info", None)
    if info is None or info.module is not module:
        info = ShardingInfo(module)
        module._sharding_info = info
    return info
