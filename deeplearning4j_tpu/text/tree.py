"""Labeled binary trees + a lightweight parser for RNTN-style models.

Capability match of the reference's ``models/featuredetectors/autoencoder/
recursive/Tree.java`` (471 LoC general labeled tree with gold labels, spans,
error accumulation) and the role of ``text/corpora/treeparser/TreeParser
.java:41`` (the reference drives an external OpenNLP/UIMA parser; here the
equivalents are (a) a Penn-Treebank s-expression reader for annotated
corpora like Stanford Sentiment, and (b) a trivial right-branching
binarizer for raw sentences so RNTN runs without an external parser).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator


@dataclass
class Tree:
    label: str = ""
    gold_label: int = -1
    word: str | None = None                  # leaves only
    children: list["Tree"] = field(default_factory=list)
    prediction: object = None                # filled by models
    error: float = 0.0

    # ------------------------------------------------------------------ structure
    def is_leaf(self) -> bool:
        return not self.children

    def is_pre_terminal(self) -> bool:
        return len(self.children) == 1 and self.children[0].is_leaf()

    def leaves(self) -> list["Tree"]:
        if self.is_leaf():
            return [self]
        return [l for c in self.children for l in c.leaves()]

    def words(self) -> list[str]:
        return [l.word for l in self.leaves() if l.word is not None]

    def subtrees(self) -> Iterator["Tree"]:
        yield self
        for c in self.children:
            yield from c.subtrees()

    def depth(self) -> int:
        return 1 if self.is_leaf() else 1 + max(c.depth() for c in self.children)

    def assign_spans(self, start: int = 0) -> int:
        """Assign (start, end) leaf spans to every subtree; call on the ROOT.
        Returns this subtree's end position."""
        if self.is_leaf():
            self._span = (start, start + 1)
            return start + 1
        pos = start
        for c in self.children:
            pos = c.assign_spans(pos)
        self._span = (start, pos)
        return pos

    def span(self) -> tuple[int, int]:
        """(start, end) token span in the root's leaf order.  Requires
        ``root.assign_spans()`` first; standalone trees get (0, n_leaves)."""
        if not hasattr(self, "_span"):
            self.assign_spans()
        return self._span

    def error_sum(self) -> float:
        return sum(t.error for t in self.subtrees())

    # ------------------------------------------------------------------ serde
    def to_sexpr(self) -> str:
        if self.is_leaf():
            return self.word or ""
        kids = " ".join(c.to_sexpr() for c in self.children)
        return f"({self.label} {kids})"

    def __str__(self) -> str:
        return self.to_sexpr()


def parse_sexpr(s: str) -> Tree:
    """Penn-treebank style: ``(3 (2 word) (1 (0 other) (2 words)))`` — the
    node label may be a sentiment class id or a syntactic tag."""
    tokens = s.replace("(", " ( ").replace(")", " ) ").split()
    pos = 0

    def parse() -> Tree:
        nonlocal pos
        assert tokens[pos] == "(", f"expected ( at {pos}"
        pos += 1
        label = tokens[pos]
        pos += 1
        node = Tree(label=label)
        try:
            node.gold_label = int(label)
        except ValueError:
            pass
        while tokens[pos] != ")":
            if tokens[pos] == "(":
                node.children.append(parse())
            else:
                node.children.append(Tree(word=tokens[pos], label=label))
                pos += 1
        pos += 1
        return node

    tree = parse()
    return tree


def right_branching(words: list[str], label: int = -1) -> Tree:
    """Binarize a raw token list right-branching — lets RNTN train without an
    external constituency parser (documented deviation: the reference calls
    out to OpenNLP/ClearTK)."""
    assert words
    if len(words) == 1:
        return Tree(word=words[0], gold_label=label)
    node = Tree(gold_label=label)
    node.children = [Tree(word=words[0], gold_label=label),
                     right_branching(words[1:], label)]
    return node


def binarize(tree: Tree) -> Tree:
    """Left-factor n-ary nodes into binary ones (RNTN needs binary trees)."""
    if tree.is_leaf():
        return tree
    kids = [binarize(c) for c in tree.children]
    while len(kids) > 2:
        merged = Tree(label=tree.label, gold_label=tree.gold_label,
                      children=[kids[0], kids[1]])
        kids = [merged] + kids[2:]
    out = Tree(label=tree.label, gold_label=tree.gold_label, word=tree.word)
    out.children = kids
    return out
