"""Optimization engine tests: transforms chain (AdaGrad parity with the
reference's learner), solvers on convex objectives, line search, HF."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, OptimizationAlgorithm
from deeplearning4j_tpu.optimize import transforms as tfm
from deeplearning4j_tpu.optimize.api import EpsTermination, Norm2Termination, ScoreIterationListener
from deeplearning4j_tpu.optimize.solvers import (
    BackTrackLineSearch,
    ConjugateGradient,
    IterationGradientDescent,
    LBFGS,
    Solver,
    StochasticHessianFree,
)


def quadratic_objective(center):
    """f(p) = 0.5*||p - c||^2 — minimized at c."""
    def obj(params, key):
        diff = params["x"] - center
        loss = 0.5 * jnp.sum(diff ** 2)
        return loss, {"x": diff}
    return obj


def rosenbrock_objective():
    def f(params, key=None):
        x, y = params["x"][0], params["x"][1]
        return (1 - x) ** 2 + 100 * (y - x * x) ** 2

    def obj(params, key):
        return f(params, key), jax.grad(lambda p: f(p))(params)
    return obj


def _conf(algo, iters=100, **kw):
    kw.setdefault("lr", 0.1)
    return NeuralNetConfiguration(optimization_algo=algo, num_iterations=iters,
                                  use_adagrad=False, momentum=0.0, **kw)


def test_adagrad_transform_math():
    """First AdaGrad step: lr * g / sqrt(g^2 + eps) ≈ lr (mirror of
    AdaGradTest)."""
    t = tfm.adagrad(lr=0.5, eps=1e-12)
    params = {"w": jnp.array([1.0, 2.0])}
    grads = {"w": jnp.array([10.0, -4.0])}
    state = t.init(params)
    out, state = t.update(grads, state, params, 0)
    np.testing.assert_allclose(np.asarray(out["w"]), [0.5, -0.5], rtol=1e-5)
    # second identical step shrinks by sqrt(2)
    out2, _ = t.update(grads, state, params, 1)
    np.testing.assert_allclose(np.asarray(out2["w"]), [0.5 / np.sqrt(2), -0.5 / np.sqrt(2)], rtol=1e-4)


def test_momentum_schedule_transform():
    t = tfm.momentum(0.5, {5: 0.9})
    params = {"w": jnp.zeros(2)}
    state = t.init(params)
    g = {"w": jnp.ones(2)}
    v1, state = t.update(g, state, params, 0)   # v = 0.5*0 + 1
    np.testing.assert_allclose(np.asarray(v1["w"]), [1, 1])
    v2, state = t.update(g, state, params, 6)   # m=0.9 → v = 0.9*1 + 1
    np.testing.assert_allclose(np.asarray(v2["w"]), [1.9, 1.9], rtol=1e-6)


def test_chain_from_conf_runs():
    conf = NeuralNetConfiguration(use_adagrad=True, momentum=0.9, l2=1e-3,
                                  use_regularization=True,
                                  constrain_gradient_to_unit_norm=True)
    t = tfm.from_conf(conf)
    params = {"w": jnp.ones(3)}
    state = t.init(params)
    out, _ = t.update({"w": jnp.ones(3)}, state, params, 0)
    np.testing.assert_allclose(float(jnp.linalg.norm(out["w"])), 1.0, rtol=1e-5)


def test_backtrack_line_search_armijo():
    value_fn = lambda p: 0.5 * jnp.sum(p["x"] ** 2)
    params = {"x": jnp.array([4.0])}
    grads = {"x": jnp.array([4.0])}
    direction = {"x": jnp.array([-4.0])}
    ls = BackTrackLineSearch(value_fn, max_iterations=10)
    step = ls.optimize(params, direction, grads, initial_step=1.0)
    assert step > 0
    new = params["x"] + step * direction["x"]
    assert abs(float(new[0])) < 4.0


@pytest.mark.parametrize("algo", [
    OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT,
    OptimizationAlgorithm.GRADIENT_DESCENT,
    OptimizationAlgorithm.CONJUGATE_GRADIENT,
    OptimizationAlgorithm.LBFGS,
    OptimizationAlgorithm.HESSIAN_FREE,
])
def test_solvers_minimize_quadratic(algo):
    center = jnp.array([3.0, -2.0, 1.0])
    obj = quadratic_objective(center)
    solver = Solver(_conf(algo, iters=200), obj)
    result = solver.optimize({"x": jnp.zeros(3)})
    np.testing.assert_allclose(np.asarray(result.params["x"]), np.asarray(center),
                               atol=0.2)
    assert result.score < 0.05


def test_lbfgs_beats_gd_on_rosenbrock():
    obj = rosenbrock_objective()
    start = {"x": jnp.array([-1.2, 1.0])}
    lbfgs = LBFGS(_conf(OptimizationAlgorithm.LBFGS, iters=300), obj,
                  terminations=[Norm2Termination(1e-6)])
    res = lbfgs.optimize(start)
    assert res.score < 1e-2


def test_hessian_free_damping_adapts():
    obj = quadratic_objective(jnp.array([1.0, 1.0]))
    hf = StochasticHessianFree(_conf(OptimizationAlgorithm.HESSIAN_FREE, iters=20),
                               obj, damping=100.0)
    res = hf.optimize({"x": jnp.zeros(2)})
    assert res.score < 1e-3
    assert hf.damping < 100.0  # good quadratic fit → damping shrinks


def test_hessian_free_gauss_newton_converges_on_nonconvex_net():
    """VERDICT r3 #7: HF on a small NON-convex net (tanh hidden layer) via
    Gauss-Newton products.  The full Hessian is indefinite here — GN is PSD
    by construction, so CG stays well-posed and HF actually trains the net."""
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.standard_normal((32, 2)), jnp.float32)
    Y = jnp.tanh(X @ jnp.asarray([[1.5], [-2.0]])) * 0.7 + 0.1

    params = {
        "w1": jnp.asarray(rng.standard_normal((2, 8)) * 0.5, jnp.float32),
        "b1": jnp.zeros((8,), jnp.float32),
        "w2": jnp.asarray(rng.standard_normal((8, 1)) * 0.5, jnp.float32),
        "b2": jnp.zeros((1,), jnp.float32),
    }

    def predict(p, key=None):
        return jnp.tanh(X @ p["w1"] + p["b1"]) @ p["w2"] + p["b2"]

    def loss_out(z):
        return jnp.mean((z - Y) ** 2)

    def objective(p, key):
        return jax.value_and_grad(lambda q: loss_out(predict(q)))(p)

    hf = StochasticHessianFree(
        _conf(OptimizationAlgorithm.HESSIAN_FREE, iters=40), objective,
        damping=1.0, gauss_newton=(predict, loss_out))
    res = hf.optimize(params)
    assert res.history[0] > 0.1, "net must start untrained"
    assert res.score < 0.01, res.history[-5:]


def test_hessian_free_cg_runs_without_host_sync_per_iter():
    """The CG solve is one compiled call: its result is a device array and
    repeated solves reuse the compiled while_loop (no growing jit cache)."""
    obj = quadratic_objective(jnp.array([1.0, 2.0]))
    hf = StochasticHessianFree(
        _conf(OptimizationAlgorithm.HESSIAN_FREE, iters=2), obj, damping=0.1)
    p = {"x": jnp.zeros(2)}
    _, g = obj(p, None)
    d1 = hf._cg_solve(p, g, jax.random.key(0), hf.damping)
    assert isinstance(d1["x"], jax.Array)
    cg_compiled = hf._jit_cg
    hf._cg_solve(p, g, jax.random.key(1), hf.damping * 1.5)
    assert hf._jit_cg is cg_compiled   # damping is a traced arg, not a retrace
    # (H + λI)d = -g with H=I, λ=0.1: d = -g / 1.1
    np.testing.assert_allclose(np.asarray(d1["x"]),
                               -np.asarray(g["x"]) / 1.1, rtol=1e-5)


def test_listener_and_termination():
    obj = quadratic_objective(jnp.array([1.0]))
    listener = ScoreIterationListener(print_every=1000)
    solver = IterationGradientDescent(
        _conf(OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT, iters=500, lr=0.5),
        obj, listeners=[listener], terminations=[EpsTermination(1e-9)])
    res = solver.optimize({"x": jnp.zeros(1)})
    assert res.converged and res.iterations < 500
    assert len(listener.scores) == res.iterations


def test_adam_bias_correction_first_step():
    """First Adam update ≈ sign(g) * lr regardless of gradient scale (the
    bias-corrected m/sqrt(v) is ±1 for a constant gradient)."""
    t = tfm.adam(lr=0.01)
    p = {"x": jnp.zeros(3)}
    g = {"x": jnp.array([10.0, -0.001, 2.0])}
    s = t.init(p)
    u, s = t.update(g, s, p, 0)
    np.testing.assert_allclose(np.asarray(u["x"]),
                               0.01 * np.sign([10.0, -0.001, 2.0]), rtol=1e-3)


def test_adam_minimizes_quadratic():
    t = tfm.adam(lr=0.1)
    p = {"x": jnp.array([5.0, -3.0])}
    s = t.init(p)
    for i in range(300):
        g = {"x": p["x"] - jnp.array([1.0, 2.0])}
        u, s = t.update(g, s, p, i)
        p = tfm.apply_updates(p, u)
    np.testing.assert_allclose(np.asarray(p["x"]), [1.0, 2.0], atol=1e-2)


def test_adamw_decays_matrices_not_biases():
    """Decoupled decay hits ndim>=2 leaves only."""
    t = tfm.adamw(lr=0.1, weight_decay=0.5)
    p = {"W": jnp.ones((2, 2)), "b": jnp.ones((2,))}
    g = {"W": jnp.zeros((2, 2)), "b": jnp.zeros((2,))}
    s = t.init(p)
    u, s = t.update(g, s, p, 0)
    # zero gradient: W update = lr * wd * W, b update = 0
    np.testing.assert_allclose(np.asarray(u["W"]), 0.1 * 0.5 * np.ones((2, 2)), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(u["b"]), 0.0, atol=1e-9)


def test_warmup_cosine_schedule_shape():
    sched = tfm.warmup_cosine(1.0, 10, 110, end=0.1)
    assert float(sched(0)) == 0.0
    np.testing.assert_allclose(float(sched(10)), 1.0, rtol=1e-6)
    np.testing.assert_allclose(float(sched(5)), 0.5, rtol=1e-6)
    np.testing.assert_allclose(float(sched(110)), 0.1, rtol=1e-6)
    np.testing.assert_allclose(float(sched(60)), 0.55, rtol=1e-6)  # midpoint


def test_warmup_linear_schedule_shape():
    sched = tfm.warmup_linear(1.0, 10, 110, end=0.0)
    np.testing.assert_allclose(float(sched(10)), 1.0, rtol=1e-6)
    np.testing.assert_allclose(float(sched(60)), 0.5, rtol=1e-6)
    np.testing.assert_allclose(float(sched(110)), 0.0, atol=1e-7)


def test_from_conf_l2_after_adaptive_lr():
    """ADVICE fix: the reference subtracts l2*w AFTER adagrad scaling, so
    with zero gradient the update must be exactly l2*w (not rescaled)."""
    conf = NeuralNetConfiguration(lr=0.5, use_adagrad=True, momentum=0.0,
                                  use_regularization=True, l2=0.1)
    t = tfm.from_conf(conf)
    p = {"W": jnp.full((2, 2), 3.0)}
    g = {"W": jnp.zeros((2, 2))}
    s = t.init(p)
    u, _ = t.update(g, s, p, 0)
    np.testing.assert_allclose(np.asarray(u["W"]), 0.1 * 3.0, rtol=1e-5)


def test_state_spec_mirrors_params():
    from jax.sharding import PartitionSpec as P
    tx = tfm.adamw(lr=0.1)
    ps = {"W": P("tp", None), "b": P()}
    spec = tx.state_spec(ps)
    # chain(scale_by_adam, add_decayed_weights, scale_by_schedule)
    assert spec[0] == (ps, ps)
    assert spec[1] == () and spec[2] == ()


def test_decay_mask_override_is_context_local():
    """The override stack is a ContextVar, not module state: concurrent
    threads see only their own override, and the main context keeps the
    ndim >= 2 heuristic while workers hold overrides open."""
    import threading

    params = {"w": jnp.ones((2, 2)), "b": jnp.ones((2,))}
    default = tfm.decay_leaf_mask(params)
    assert default == {"w": True, "b": False}

    results = {}
    barrier = threading.Barrier(3, timeout=10)

    def worker(name, mask):
        with tfm.decay_mask_override(mask):
            barrier.wait()           # every context holds its override open
            results[name] = tfm.decay_leaf_mask(params)

    masks = {"a": {"w": False, "b": True}, "b": {"w": True, "b": True}}
    threads = [threading.Thread(target=worker, args=(n, m))
               for n, m in masks.items()]
    for t in threads:
        t.start()
    barrier.wait()
    main_view = tfm.decay_leaf_mask(params)      # no override HERE
    for t in threads:
        t.join()
    assert results == masks
    assert main_view == default

    # nesting: innermost wins, None re-enables the heuristic, exit restores
    with tfm.decay_mask_override({"w": False, "b": False}):
        with tfm.decay_mask_override(None):
            assert tfm.decay_leaf_mask(params) == default
        assert tfm.decay_leaf_mask(params) == {"w": False, "b": False}
    assert tfm.decay_leaf_mask(params) == default
