"""Continuous-batching inference engine (DESIGN.md §13).

Two workloads over one discipline — keep the device batch full, keep the
host off the per-token path:

- :class:`InferenceEngine`: slot-based continuous batching for the
  flagship transformer.  The KV cache is a POOL of ``slots`` rows
  (``(S, max_len, H, Dh)`` per layer); every decode step advances ALL
  occupied slots one token through :func:`decode_step` with per-slot
  positions, new sequences are admitted into free rows between steps
  (prefill on a batch-of-1 cache, then one scatter into the pool), and a
  finished sequence (EOS / length budget) frees its row for the next
  arrival.  Sequences at different depths share every device batch —
  ragged traffic cannot drain the batch the way static batching does.

- :class:`BatchScorer`: batched forward/score for ``MultiLayerNetwork``
  and zoo models — concurrent callers coalesce into one padded
  (power-of-two bucket) device batch through any row-wise ``fn``.

Hot-path rules (PR-2/PR-3 heritage): the decode loop dispatches
``resolve_every`` steps back-to-back under ``hot_loop_guard()`` — zero
host syncs per token — and resolves the emitted-token stack at ONE
``allow_transfers()`` fence per segment, where EOS/length bookkeeping,
admissions, and metrics publication happen.  Every jitted entry donates
the engine state, so the cache pool is updated in place.

RNG parity contract: slot ``s`` runs the exact draw sequence of
``Transformer.sample(..., key=jax.random.key(seed), kv_cache=True)`` —
split once per generated token, sample from the second half — so a
served continuation is token-identical to the offline sampler under the
same seed (the tier-1 acceptance test).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..analysis.runtime import allow_transfers, hot_loop_guard
from ..models.transformer import (decode_step, init_decode_cache,
                                  reset_cache_slots)
from ..observability import METRICS, trace
from ..parallel.checkpoint import CheckpointManager
from ..parallel.compile_cache import setup_compile_cache
from ..resilience.faults import FAULTS
from .batcher import (Completion, GenerateRequest, PendingResult,
                      RequestQueue, ScoreRequest)

#: unit-interval buckets for fill-ratio histograms (observe_time is the
#: registry's generic histogram feed; these are ratios, not seconds)
FILL_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Engine knobs (the model's own shape lives in TransformerConfig)."""

    slots: int = 4                  # concurrent sequences in the device batch
    resolve_every: int = 4          # decode steps dispatched per host fence
    max_queue: int = 64             # RequestQueue bound (429 beyond)
    max_batch_delay_ms: float = 2.0  # idle coalescing window
    min_prefill_bucket: int = 8     # floor of the prompt bucket ladder
    idle_wait_s: float = 0.05       # queue poll period while no slot is live
    default_eos_id: int | None = None
    int8_decode: bool = False       # serve int8 weight-quantized FFN/head
    #                                 (opt-in; adoption gated on token-level
    #                                 top-1 agreement with f32 decode)


@dataclasses.dataclass
class _Slot:
    """Host-side record of one occupied cache row."""

    pending: PendingResult
    delivered: list = dataclasses.field(default_factory=list)
    admitted_s: float = 0.0
    first_token_s: float | None = None


class InferenceEngine:
    """Continuous-batching decode over a trained ``TransformerLM``.

    ``params`` may be passed directly, or loaded from ``checkpoint`` (a
    directory path or a :class:`CheckpointManager`) — the engine opens
    checkpoint directories READ-ONLY and restores ``latest_valid_step()``.
    ``model.init`` shapes the restore template, so the checkpoint must
    match ``model.cfg``.
    """

    def __init__(self, model, params=None, checkpoint=None,
                 cfg: ServingConfig = ServingConfig(),
                 compile_cache_dir: str | None = None):
        # PR-2 warmup integration: with a persistent cache dir configured
        # (env or explicit), the warmup compiles below hit disk
        setup_compile_cache(compile_cache_dir)
        self.model = model
        self.cfg = cfg
        self._queue = RequestQueue(cfg.max_queue, cfg.max_batch_delay_ms)
        self._ckpt: CheckpointManager | None = None
        self._loaded_step: int | None = None
        if checkpoint is not None:
            self._ckpt = (checkpoint if isinstance(checkpoint, CheckpointManager)
                          else CheckpointManager.open_read_only(checkpoint))
        if params is None:
            if self._ckpt is None:
                raise ValueError("need params or a checkpoint to serve from")
            step = self._ckpt.latest_valid_step()
            if step is None:
                raise FileNotFoundError(
                    f"no verified checkpoint under {self._ckpt.directory}")
            template = model.init(jax.random.key(0))
            restored = self._ckpt.restore(template, step=step)
            params = restored["params"]
            self._loaded_step = restored["step"]
        # _lock guards the params swap AND the slot bookkeeping shared
        # between the serve thread and callers (stop/stats/HTTP handlers);
        # _state is deliberately OUTSIDE it — serve-thread-owned, see
        # warmup().  The guarded-by annotations are the LK01 contract:
        # every non-__init__ write must hold the lock.
        self._lock = threading.Lock()
        # _raw_params is the unquantized tree (also the reload restore
        # template — checkpoints never contain *_q leaves); _params is
        # what decode actually reads, int8-quantized when opted in
        self._raw_params = params                # guarded-by: self._lock
        self._params = self._maybe_quantize(params)  # guarded-by: self._lock
        self._state = self._init_state()
        self._step_fn = jax.jit(self._build_step(), donate_argnums=(1,))
        self._step_compiled = False
        self._admit_fns: dict[int, Callable] = {}    # guarded-by: self._lock
        self._slots: dict[int, _Slot] = {}           # guarded-by: self._lock
        self._free: list[int] = list(range(cfg.slots))  # guarded-by: self._lock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._admitted = 0                           # guarded-by: self._lock
        self._completed = 0                          # guarded-by: self._lock

    def _maybe_quantize(self, params):
        """The serving tree decode reads: unchanged by default; with
        ``int8_decode`` the bandwidth-heavy matrices (FFN w1/w2, LM head)
        are replaced by int8 + per-channel-scale copies, and
        ``decode_step``/``_ffn`` pick the int8 path on key presence."""
        if not self.cfg.int8_decode:
            return params
        from ..ops.pallas.matmul_int8 import quantize_params_for_decode
        with allow_transfers(), METRICS.time("serving.quantize"):
            return quantize_params_for_decode(params, self.model.cfg)

    # ------------------------------------------------------------ device state
    def _init_state(self) -> dict:
        cfg = self.model.cfg
        S = self.cfg.slots
        return {
            "cache": init_decode_cache(cfg, S),
            "toks": jnp.zeros((S, cfg.max_len), jnp.int32),
            "pos": jnp.zeros((S,), jnp.int32),
            "limit": jnp.zeros((S,), jnp.int32),
            "temp": jnp.zeros((S,), jnp.float32),
            "keys": jax.random.split(jax.random.key(0), S),
            "active": jnp.zeros((S,), bool),
        }

    def _build_step(self) -> Callable:
        cfg = self.model.cfg

        def step(params, state):
            """Advance every occupied slot one token.

            Inactive / exhausted rows still flow through the batched
            matmuls (masked no-ops — cheaper than reshaping the batch),
            but their RNG keys, positions and token buffers are frozen
            and they emit -1.
            """
            toks, pos = state["toks"], state["pos"]
            temp, active, limit = state["temp"], state["active"], state["limit"]
            row = jnp.arange(toks.shape[0])
            cur = toks[row, pos]
            logits, cache = decode_step(params, state["cache"], cur, pos, cfg)
            # per-slot RNG, exactly Transformer.sample's kv stream: split
            # the slot key, carry the first half, draw from the second
            pair = jax.vmap(jax.random.split)(state["keys"])    # (S, 2) keys
            carry, sub = pair[:, 0], pair[:, 1]
            safe_t = jnp.where(temp > 0, temp, 1.0)
            drawn = jax.vmap(jax.random.categorical)(
                sub, logits / safe_t[:, None])
            pick = jnp.where(temp > 0, drawn.astype(jnp.int32),
                             jnp.argmax(logits, axis=-1).astype(jnp.int32))
            can = active & (pos < limit) & (pos + 1 < cfg.max_len)
            emitted = jnp.where(can, pick, -1)
            new_pos = jnp.where(can, pos + 1, pos)
            toks = toks.at[row, new_pos].set(
                jnp.where(can, pick, toks[row, new_pos]))
            kd = jax.random.key_data(state["keys"])
            keys = jax.random.wrap_key_data(
                jnp.where(can[:, None], jax.random.key_data(carry), kd))
            new_state = dict(state, cache=cache, toks=toks, pos=new_pos,
                             keys=keys)
            return new_state, emitted

        return step

    # ------------------------------------------------------------ prefill
    def _prompt_bucket(self, n: int) -> int:
        """Power-of-two prompt ladder (the PR-2 pad-batch discipline):
        one compiled prefill per bucket, so recompiles are bounded by
        ``log2(max_len)`` regardless of prompt-length diversity."""
        b = self.cfg.min_prefill_bucket
        while b < n:
            b <<= 1
        return min(b, self.model.cfg.max_len)

    def _admit_for(self, bucket: int) -> Callable:
        with self._lock:
            cached = self._admit_fns.get(bucket)
        if cached is not None:
            return cached
        cfg = self.model.cfg

        def admit(params, state, prompt, p_len, slot, key, temp, max_new):
            """Prefill ``prompt[:p_len]`` on a batch-of-1 cache through
            the SAME ``decode_step`` the steady loop uses (numerics cannot
            diverge from ``Transformer.sample``'s kv path), then scatter
            the row into cache-pool row ``slot``.  Iterations past
            ``p_len - 1`` are masked no-ops: one executable per bucket."""
            cache1 = init_decode_cache(cfg, 1)
            last = jnp.maximum(p_len - 2, 0)

            def body(i, c):
                ii = jnp.minimum(i, last)
                _, c_new = decode_step(
                    params, c, lax.dynamic_slice(prompt, (ii,), (1,)), ii, cfg)
                use = i < p_len - 1
                return jax.tree_util.tree_map(
                    lambda a, b: jnp.where(use, a, b), c_new, c)

            cache1 = lax.fori_loop(0, bucket, body, cache1)
            cache = [
                {"k": lax.dynamic_update_slice_in_dim(c["k"], c1["k"], slot,
                                                      axis=0),
                 "v": lax.dynamic_update_slice_in_dim(c["v"], c1["v"], slot,
                                                      axis=0)}
                for c, c1 in zip(state["cache"], cache1)]
            toks = lax.dynamic_update_slice(
                state["toks"], prompt[None, :], (slot, jnp.int32(0)))

            def put1(arr, v):
                return lax.dynamic_update_slice(
                    arr, jnp.reshape(v, (1,)).astype(arr.dtype), (slot,))

            kd = lax.dynamic_update_slice(
                jax.random.key_data(state["keys"]),
                jax.random.key_data(key)[None], (slot, jnp.int32(0)))
            return dict(
                state,
                cache=cache,
                toks=toks,
                # sample() prefills tokens 0..P-2; the first engine step
                # then processes token P-1 and draws the first new token
                pos=put1(state["pos"], p_len - 1),
                limit=put1(state["limit"], p_len - 1 + max_new),
                temp=put1(state["temp"], temp),
                active=put1(state["active"], True),
                keys=jax.random.wrap_key_data(kd),
            )

        prefill = jax.jit(admit, donate_argnums=(1,))
        with self._lock:
            self._admit_fns[bucket] = prefill
        METRICS.increment("serving.prefill.recompile")
        return prefill

    # ------------------------------------------------------------ submission
    def submit(self, prompt, max_new_tokens: int, temperature: float = 0.0,
               seed: int = 0, eos_id: int | None = None,
               deadline_ms: float | None = None) -> PendingResult:
        """Validate + enqueue; returns a handle whose ``result()`` blocks.
        Raises ``ValueError`` on malformed requests (HTTP 400) and
        :class:`~.batcher.QueueFull` under backpressure (HTTP 429)."""
        cfg = self.model.cfg
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if any(not 0 <= t < cfg.vocab_size for t in prompt):
            raise ValueError(f"prompt token out of range [0, {cfg.vocab_size})")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt) + max_new_tokens > cfg.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_len ({cfg.max_len})")
        req = GenerateRequest(
            prompt=prompt, max_new_tokens=int(max_new_tokens),
            temperature=float(temperature), seed=int(seed),
            eos_id=eos_id if eos_id is not None else self.cfg.default_eos_id,
            deadline_s=(time.monotonic() + deadline_ms / 1000.0
                        if deadline_ms else None))
        METRICS.increment("serving.requests")
        return self._queue.submit(req)

    def generate(self, prompt, max_new_tokens: int, timeout: float = 60.0,
                 **kw) -> Completion:
        """Blocking convenience wrapper over :meth:`submit`."""
        return self.submit(prompt, max_new_tokens, **kw).result(timeout)

    # ------------------------------------------------------------ serve loop
    def start(self, warmup: bool = True) -> "InferenceEngine":
        if self._thread is not None:
            return self
        if warmup:
            self.warmup()
        self._stop.clear()
        self._thread = threading.Thread(target=self._serve_loop, daemon=True,
                                        name="serving-engine")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        with self._lock:
            dead = [self._slots.pop(s) for s in list(self._slots)]
        for sl in dead:
            sl.pending._fail(
                RuntimeError("engine stopped with request in flight"))
        for p in self._queue.drain():
            p._fail(RuntimeError("engine stopped before request was admitted"))

    def __enter__(self) -> "InferenceEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def warmup(self) -> None:
        """Compile the steady-state step and the smallest prefill bucket
        before traffic (with the PR-2 persistent compile cache configured
        these are disk hits on restart) — first-request latency pays
        trace+lower cost at most once, at startup."""
        with allow_transfers(), METRICS.time("serving.warmup"):
            state, _ = self._step_fn(self._params, self._state)
            self._step_compiled = True
            bucket = self._prompt_bucket(1)
            fn = self._admit_for(bucket)
            state = fn(self._params, state,
                       jnp.zeros((bucket,), jnp.int32), jnp.int32(1),
                       jnp.int32(0), jax.random.key(0), jnp.float32(0.0),
                       jnp.int32(0))
            # the warmup admit occupied slot 0 with a dummy — deactivate.
            # graftlint: disable=LK01 — _state is serve-thread-owned (every
            # other write site runs on the serve loop); warmup runs strictly
            # before Thread.start(), which is a happens-before edge, so this
            # external-context write can never race the loop
            self._state = dict(state, active=jnp.zeros_like(state["active"]))

    def _serve_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._serve_once()
            except Exception as e:  # defensive: a wedged loop strands callers
                METRICS.increment("serving.engine.errors")
                with self._lock:
                    dead = [self._slots.pop(s) for s in list(self._slots)]
                    self._free = list(range(self.cfg.slots))
                for sl in dead:
                    sl.pending._fail(e)
                with allow_transfers():
                    self._state = self._init_state()

    def _serve_once(self) -> None:
        idle = not self._slots
        n_free = len(self._free)
        if n_free:
            batch = self._queue.take(
                n_free, block_s=self.cfg.idle_wait_s if idle else 0.0)
            if batch:
                # admission is a deliberate host<->device seam (prompt
                # upload, request bookkeeping) — annotated, off the
                # per-token path
                with allow_transfers(), trace.span("serving.admit"):
                    self._admit(batch)
        if not self._slots:
            return
        METRICS.observe_time("serving.batch_fill_ratio",
                             len(self._slots) / self.cfg.slots,
                             buckets=FILL_BUCKETS)
        t0 = time.perf_counter()
        with hot_loop_guard():
            pending = self._decode_segment()
        with allow_transfers(), trace.span("serving.resolve"):
            self._resolve(pending, t0)

    def _admit(self, batch: list[PendingResult]) -> None:
        for p in batch:
            # atomic expiry-vs-admission: a deadline that passed between
            # the queue pop and this point 504s HERE, under the queue
            # lock, instead of occupying a slot to decode tokens nobody
            # is waiting for
            if not self._queue.claim(p):
                continue
            req: GenerateRequest = p.request
            with self._lock:
                slot = self._free.pop()
                params = self._params
            try:
                bucket = self._prompt_bucket(len(req.prompt))
                prompt = np.zeros((bucket,), np.int32)
                prompt[:len(req.prompt)] = req.prompt
                admit_fn = self._admit_for(bucket)
                self._state = admit_fn(
                    params, self._state, jnp.asarray(prompt),
                    jnp.int32(len(req.prompt)), jnp.int32(slot),
                    jax.random.key(req.seed), jnp.float32(req.temperature),
                    jnp.int32(req.max_new_tokens))
            except Exception as e:
                # fail only THIS request — the slot goes back to the pool
                # and the rest of the batch still admits
                with self._lock:
                    self._free.append(slot)
                METRICS.increment("serving.engine.errors")
                p._fail(e)
                continue
            with self._lock:
                self._slots[slot] = _Slot(pending=p,
                                          admitted_s=time.monotonic())
                self._admitted += 1
            METRICS.increment("serving.admitted")

    def _decode_segment(self) -> list:
        """Dispatch ``resolve_every`` decode steps with NO host syncs —
        the emitted-token arrays stay on device until ``_resolve``."""
        out = []
        step_fn = self._step_fn
        with self._lock:
            params = self._params
        for _ in range(self.cfg.resolve_every):
            if FAULTS.check("serving.decode") is not None:
                # transient decode fault (chaos): this dispatch is skipped,
                # state is untouched, the next round retries — completions
                # stay token-identical under injection
                METRICS.increment("serving.decode.faults")
                continue
            self._state, emitted = step_fn(params, self._state)
            out.append(emitted)
        METRICS.increment("serving.decode.dispatches", len(out))
        return out

    def _resolve(self, pending: list, t0: float) -> None:
        """The per-segment fence: ONE host pull for the whole segment's
        emitted tokens, then EOS/length bookkeeping and metrics."""
        if not pending:
            return
        em = np.asarray(jax.device_get(jnp.stack(pending)))     # (k, S)
        now = time.monotonic()
        seg_s = time.perf_counter() - t0
        n_steps = len(pending)
        METRICS.observe_many("serving.decode_step", [seg_s / n_steps] * n_steps)
        delivered = 0
        for s in list(self._slots):
            sl = self._slots[s]
            req: GenerateRequest = sl.pending.request
            finish = None
            for t in em[:, s]:
                t = int(t)
                if t < 0:
                    continue
                delivered += 1
                if sl.first_token_s is None:
                    sl.first_token_s = now  # fence granularity, documented
                    METRICS.observe_time("serving.ttft",
                                         now - req.submitted_s)
                sl.delivered.append(t)
                if req.eos_id is not None and t == req.eos_id:
                    finish = "eos"
                    break
                if len(sl.delivered) >= req.max_new_tokens:
                    finish = "length"
                    break
            if finish is not None:
                self._evict(s, finish, now)
        if delivered:
            METRICS.increment("serving.tokens", delivered)
            if seg_s > 0:
                METRICS.gauge("serving.tokens_per_sec", delivered / seg_s)

    def _evict(self, s: int, finish: str, now: float) -> None:
        """Free slot ``s``: complete the caller, drop the host record,
        deactivate the row and wipe its K/V (tokens the segment over-
        decoded past EOS died here, discarded at the fence)."""
        with self._lock:
            sl = self._slots.pop(s)
            self._free.append(s)
            self._completed += 1
        mask = np.zeros((self.cfg.slots,), bool)
        mask[s] = True
        # the freed row is reusable before this wipe lands only by
        # _admit, which runs on this same serve thread — no interleave
        self._state = dict(
            self._state,
            cache=reset_cache_slots(self._state["cache"], jnp.asarray(mask)),
            active=self._state["active"].at[s].set(False))
        req = sl.pending.request
        METRICS.increment("serving.completed")
        METRICS.observe_time("serving.request_latency", now - req.submitted_s)
        sl.pending._complete(Completion(
            tokens=list(sl.delivered), finish_reason=finish,
            latency_s=now - req.submitted_s,
            ttft_s=(sl.first_token_s - req.submitted_s
                    if sl.first_token_s is not None else None)))

    # ------------------------------------------------------------ hot reload
    def reload(self) -> int:
        """Atomic hot swap to ``latest_valid_step()`` WITHOUT draining:
        in-flight segments finish on the params they dispatched with; the
        next dispatch reads the new tree.  Shapes are fixed by the config,
        so the swap hits the existing executables — no recompile, no
        pause.  Returns the loaded step."""
        if self._ckpt is None:
            raise RuntimeError("no checkpoint attached — nothing to reload")
        step = self._ckpt.latest_valid_step()
        if step is None:
            raise FileNotFoundError(
                f"no verified checkpoint under {self._ckpt.directory}")
        if step == self._loaded_step:
            return step
        with allow_transfers(), METRICS.time("serving.reload"):
            restored = self._ckpt.restore(self._raw_params, step=step)
            new_params = self._maybe_quantize(restored["params"])
        with self._lock:
            self._raw_params = restored["params"]
            self._params = new_params
        self._loaded_step = step
        METRICS.increment("serving.reloads")
        METRICS.gauge("serving.loaded_step", step)
        return step

    # ------------------------------------------------------------ stats
    def stats(self) -> dict:
        with self._lock:
            return {
                "slots": self.cfg.slots,
                "active": len(self._slots),
                "free": len(self._free),
                "queue_depth": self._queue.depth(),
                "admitted": self._admitted,
                "completed": self._completed,
                "loaded_step": self._loaded_step,
                "prefill_buckets": sorted(self._admit_fns),
                "running": self._thread is not None,
            }


class BatchScorer:
    """Coalesce concurrent single-row score calls into padded device
    batches through any row-wise pure ``fn`` (``net.output``, a zoo
    model's jitted apply, a ``partial(forward_local, ...)``).

    Rows queue through the same bounded :class:`RequestQueue` as
    generation (shared backpressure semantics); the worker pads each
    batch up to a power-of-two bucket (repeating the first row — pad
    outputs are discarded) so ``fn``'s jit cache sees at most
    ``log2(max_batch)`` shapes.
    """

    def __init__(self, fn: Callable, max_batch: int = 64,
                 max_queue: int = 256, max_batch_delay_ms: float = 2.0):
        self.fn = fn
        self.max_batch = max_batch
        self._queue = RequestQueue(max_queue, max_batch_delay_ms)
        self._shape_lock = threading.Lock()
        self._row_shape: tuple | None = None  # guarded-by: self._shape_lock
        self._row_dtype = None                # guarded-by: self._shape_lock
        self._buckets: set[int] = set()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "BatchScorer":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="serving-scorer")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        for p in self._queue.drain():
            p._fail(RuntimeError("scorer stopped before request ran"))

    def __enter__(self) -> "BatchScorer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def submit(self, x) -> PendingResult:
        x = np.asarray(x)
        # check-then-set must be atomic: two first submitters racing here
        # could each see None and publish different shapes
        with self._shape_lock:
            if self._row_shape is None:
                self._row_shape, self._row_dtype = x.shape, x.dtype
            elif x.shape != self._row_shape:
                raise ValueError(
                    f"row shape {x.shape} != first-seen {self._row_shape}")
        return self._queue.submit(ScoreRequest(x=x))

    def score(self, x, timeout: float = 30.0):
        """One row in, one output row out (blocking)."""
        return self.submit(x).result(timeout)

    def score_batch(self, xs, timeout: float = 30.0) -> np.ndarray:
        """Submit every row, gather in order — rows from concurrent
        callers interleave into shared device batches."""
        handles = [self.submit(x) for x in np.asarray(xs)]
        return np.stack([h.result(timeout) for h in handles])

    def _bucket(self, n: int) -> int:
        b = 1
        while b < n:
            b <<= 1
        return min(b, self.max_batch)

    def _loop(self) -> None:
        while not self._stop.is_set():
            batch = self._queue.take(self.max_batch, block_s=0.05)
            if not batch:
                continue
            try:
                self._run(batch)
            except Exception as e:
                METRICS.increment("serving.score.errors")
                for p in batch:
                    p._fail(e)

    def _run(self, batch: list[PendingResult]) -> None:
        n = len(batch)
        bucket = self._bucket(n)
        xs = np.stack([p.request.x for p in batch])
        if bucket > n:
            xs = np.concatenate(
                [xs, np.broadcast_to(xs[:1], (bucket - n,) + xs.shape[1:])])
        if bucket not in self._buckets:
            self._buckets.add(bucket)
            METRICS.increment("serving.score.recompile")
        with METRICS.time("serving.score_batch"):
            ys = np.asarray(self.fn(xs))
        METRICS.observe_time("serving.score.batch_fill", n / bucket,
                             buckets=FILL_BUCKETS)
        METRICS.increment("serving.score.rows", n)
        for i, p in enumerate(batch):
            p._complete(ys[i])
