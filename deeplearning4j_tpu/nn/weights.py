"""Weight initialization schemes.

Mirrors ``nn/weights/WeightInit.java:7-16`` + ``WeightInitUtil.java`` of the
reference: VI (Glorot-like fan-sum uniform), ZERO, SIZE, DISTRIBUTION,
NORMALIZED, UNIFORM.  Stateless: every init takes an explicit threefry key.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.dtypes import get_policy
from .conf import Distribution, NeuralNetConfiguration, WeightInit


def init_weights(key, shape: tuple[int, ...], scheme: WeightInit,
                 dist: Distribution = Distribution.NORMAL, dist_std: float = 1e-2,
                 dtype=None) -> jnp.ndarray:
    """Create a weight matrix per the named scheme.

    VI follows the reference formula: U(-r, r) with
    r = sqrt(6) / sqrt(fan_in + fan_out + 1)  (``WeightInitUtil.java``).
    """
    dtype = dtype or get_policy().param_dtype
    fan_in = shape[0] if len(shape) >= 1 else 1
    fan_out = shape[-1] if len(shape) >= 2 else 1
    scheme = WeightInit(scheme)
    if scheme == WeightInit.ZERO:
        return jnp.zeros(shape, dtype)
    if scheme == WeightInit.VI:
        r = jnp.sqrt(6.0) / jnp.sqrt(fan_in + fan_out + 1.0)
        return jax.random.uniform(key, shape, dtype, -r, r)
    if scheme == WeightInit.SIZE:
        # scale by 1/sqrt(fan_in)
        return jax.random.normal(key, shape, dtype) / jnp.sqrt(float(fan_in))
    if scheme == WeightInit.UNIFORM:
        a = 1.0 / float(fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == WeightInit.NORMALIZED:
        w = jax.random.uniform(key, shape, dtype)
        return (w - w.mean()) / (w.std() + 1e-12)
    if scheme == WeightInit.DISTRIBUTION:
        if Distribution(dist) == Distribution.UNIFORM:
            return jax.random.uniform(key, shape, dtype, -dist_std, dist_std)
        return dist_std * jax.random.normal(key, shape, dtype)
    raise ValueError(f"unknown weight init {scheme}")


def init_from_conf(key, shape: tuple[int, ...], conf: NeuralNetConfiguration,
                   dtype=None) -> jnp.ndarray:
    return init_weights(key, shape, conf.weight_init, conf.dist, conf.dist_std, dtype)
