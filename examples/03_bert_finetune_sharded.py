"""Fine-tune a transformer classifier over an explicit SPMD device mesh.

The BERT-fine-tune north star (BASELINE.md) in miniature: build a
bidirectional transformer, attach a classification head, and run the
AdamW fine-tune step jitted over a (dp, sp, tp) mesh — the same program
shape the framework uses on a TPU pod slice. Here the mesh is 8 virtual
CPU devices so the example runs anywhere; on real hardware only the mesh
construction changes.

Run:  python examples/03_bert_finetune_sharded.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp

from deeplearning4j_tpu.models.transformer import TransformerConfig, TransformerLM
from deeplearning4j_tpu.optimize import transforms as T
from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh


def main():
    cfg = TransformerConfig(vocab_size=256, d_model=64, n_heads=4, n_layers=2,
                            d_ff=128, max_len=32, causal=False,
                            dtype=jnp.float32, remat=False)
    mesh = make_mesh(MeshSpec(dp=2, sp=2, tp=2))
    model = TransformerLM(cfg, mesh=mesh)

    tree = model.place(model.init_finetune(jax.random.key(0), n_classes=2),
                       model.finetune_specs())
    tx = T.adamw(T.warmup_linear(3e-3, 5, 200), weight_decay=0.01)
    opt = model.init_opt(tree, tx)
    step = model.build_finetune_step(tx)

    # synthetic task: does token id 7 appear anywhere in the sequence?
    tokens = jax.random.randint(jax.random.key(3), (32, 32), 0, cfg.vocab_size)
    labels = jnp.any(tokens == 7, axis=1).astype(jnp.int32)

    # async hot loop: losses stay on device — float() every step would
    # stall dispatch; one block_until_ready fence resolves the whole run
    losses = []
    for _ in range(40):
        tree, opt, loss = step(tree, opt, tokens, labels)
        losses.append(loss)
    losses = [float(l) for l in jax.block_until_ready(losses)]
    print(f"mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))}")
    print(f"loss: {losses[0]:.4f} -> {losses[-1]:.4f}")
    assert losses[-1] < losses[0], "fine-tune loss should drop"


if __name__ == "__main__":
    main()
