"""ctypes bindings for the native host runtime, with silent fallbacks.

``lib()`` returns the loaded shared library or None; call sites check and
fall back to pure Python.  The library is built on demand at most once per
process (cheap g++ compile, cached on disk).
"""

from __future__ import annotations

import ctypes
from pathlib import Path

import numpy as np

_LIB: ctypes.CDLL | None = None
_TRIED = False
_LIB_PATH = Path(__file__).parent / "libdl4jtpu_host.so"


def lib() -> ctypes.CDLL | None:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    src = Path(__file__).parent / "src" / "host_runtime.cpp"
    stale = (_LIB_PATH.exists() and src.exists()
             and src.stat().st_mtime > _LIB_PATH.stat().st_mtime)
    if not _LIB_PATH.exists() or stale:
        from .build import build
        if build(verbose=False) is None and not _LIB_PATH.exists():
            return None
    try:
        l = ctypes.CDLL(str(_LIB_PATH))
    except OSError:
        return None
    l.drt_count_tokens.restype = ctypes.c_void_p
    l.drt_count_tokens.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                   ctypes.POINTER(ctypes.c_int64)]
    l.drt_free.argtypes = [ctypes.c_void_p]
    l.drt_skipgram_pairs.restype = ctypes.c_int64
    l.drt_skipgram_pairs.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64, ctypes.c_int32, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64]
    l.drt_parse_csv_floats.restype = ctypes.c_int64
    l.drt_parse_csv_floats.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64]
    if hasattr(l, "drt_cooccurrence"):   # absent in a stale pre-built .so
        l.drt_cooccurrence.restype = ctypes.c_void_p
        l.drt_cooccurrence.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int32, ctypes.POINTER(ctypes.c_int64)]
    if hasattr(l, "drt_parse_svmlight"):
        l.drt_parse_svmlight.restype = ctypes.c_int64
        l.drt_parse_svmlight.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int64)]
    _LIB = l
    return _LIB


def count_tokens(sentences, tokenizer_factory) -> dict[str, float] | None:
    """Native tokenize+count.  Only valid for the default tokenizer family
    (lowercase + strip punctuation + whitespace split); returns None for
    custom tokenizers so the caller uses the Python path."""
    from ..text.tokenization import (CommonPreprocessor, DefaultTokenizer,
                                     DefaultTokenizerFactory)
    if not isinstance(tokenizer_factory, DefaultTokenizerFactory):
        return None
    if not isinstance(tokenizer_factory.pre, (CommonPreprocessor, type(None))):
        return None
    if tokenizer_factory.pre is None:
        return None  # native path lowercases; plain tokenizer must not
    l = lib()
    if l is None:
        return None
    joined = "\n".join(sentences)
    if not joined.isascii():
        # the C fast path implements Python's \w semantics for ASCII only;
        # Unicode corpora take the exact Python tokenizer
        return None
    text = joined.encode("utf-8")
    out_len = ctypes.c_int64(0)
    ptr = l.drt_count_tokens(text, len(text), ctypes.byref(out_len))
    if not ptr:
        return None
    try:
        raw = ctypes.string_at(ptr, out_len.value).decode("utf-8")
    finally:
        l.drt_free(ptr)
    counts: dict[str, float] = {}
    for line in raw.splitlines():
        if "\t" in line:
            w, c = line.rsplit("\t", 1)
            counts[w] = float(c)
    return counts


def skipgram_pairs(sentence_indices, window: int, seed: int):
    """Native (center, context) generation; None -> use the Python path."""
    l = lib()
    if l is None or not sentence_indices:
        return None
    tokens = np.concatenate(sentence_indices).astype(np.int32)
    offsets = np.zeros(len(sentence_indices) + 1, np.int64)
    np.cumsum([len(s) for s in sentence_indices], out=offsets[1:])
    tok_p = tokens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
    off_p = offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
    n = l.drt_skipgram_pairs(tok_p, off_p, len(sentence_indices), window,
                             seed, None, None, 0)
    if n <= 0:
        return (np.zeros(0, np.int32), np.zeros(0, np.int32)) if n == 0 else None
    centers = np.empty(n, np.int32)
    contexts = np.empty(n, np.int32)
    wrote = l.drt_skipgram_pairs(
        tok_p, off_p, len(sentence_indices), window, seed,
        centers.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        contexts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n)
    if wrote != n:
        return None
    return centers, contexts


def cooccurrence(sentence_indices, window: int):
    """Native window-weighted co-occurrence accumulation (the GloVe host
    hot loop).  Returns (rows, cols, vals) arrays or None -> Python path."""
    l = lib()
    if l is None or not hasattr(l, "drt_cooccurrence") or not sentence_indices:
        return None
    tokens = np.concatenate(sentence_indices).astype(np.int32)
    offsets = np.zeros(len(sentence_indices) + 1, np.int64)
    np.cumsum([len(s) for s in sentence_indices], out=offsets[1:])
    out_bytes = ctypes.c_int64(0)
    ptr = l.drt_cooccurrence(
        tokens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(sentence_indices), window, ctypes.byref(out_bytes))
    if not ptr:
        return None
    try:
        raw = ctypes.string_at(ptr, out_bytes.value)
    finally:
        l.drt_free(ptr)
    n = int(np.frombuffer(raw[:8], np.int64)[0])
    rec = np.frombuffer(raw[8:], np.uint8).reshape(n, 12)
    rows = rec[:, 0:4].copy().view(np.int32)[:, 0]
    cols = rec[:, 4:8].copy().view(np.int32)[:, 0]
    vals = rec[:, 8:12].copy().view(np.float32)[:, 0]
    return rows, cols, vals


def parse_csv_floats(text: str, n_cols: int) -> np.ndarray | None:
    l = lib()
    if l is None:
        return None
    data = text.encode("utf-8")
    max_rows = text.count("\n") + 2
    out = np.empty((max_rows, n_cols), np.float32)
    rows = l.drt_parse_csv_floats(
        data, len(data), n_cols,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), max_rows)
    if rows < 0:
        return None
    return out[:rows]


def parse_svmlight(data: bytes, num_features: int):
    """Native svmlight parse of a text buffer -> (dense features, float
    labels, n_skipped_out_of_range); None -> use the Python parser (lib
    missing, stale .so, or malformed input needing Python's exact errors)."""
    l = lib()
    if l is None or not hasattr(l, "drt_parse_svmlight"):
        return None
    max_rows = data.count(b"\n") + 2
    feats = np.zeros((max_rows, num_features), np.float32)   # sparse rows
    labels = np.empty(max_rows, np.float32)
    skipped = ctypes.c_int64(0)
    rows = l.drt_parse_svmlight(
        data, len(data), num_features,
        feats.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        max_rows, ctypes.byref(skipped))
    if rows < 0:
        return None
    return feats[:rows], labels[:rows], int(skipped.value)
